"""BLS12-381 curve groups G1 (over Fp) and G2 (over Fp2).

E1:  y^2 = x^3 + 4        over Fp
E2:  y^2 = x^3 + 4(1+u)   over Fp2   (M-twist of E1)

Points are affine tuples (x, y) with None representing the identity. Affine
arithmetic with Python bigints is fast enough for the reference role; the
batched JAX engine uses Jacobian coordinates (charon_tpu/ops).

Serialization follows the ZCash/eth2 compressed format (48-byte G1, 96-byte
G2, flag bits in the 3 MSBs), matching the reference's wire types
(ref: tbls/tbls.go:16-25 — PublicKey [48]byte, Signature [96]byte).
"""

from __future__ import annotations

from charon_tpu.crypto.fields import (
    FP2_ONE,
    FP2_ZERO,
    P,
    R,
    X_ABS,
    XI,
    fp2_add,
    fp2_conj,
    fp2_inv,
    fp2_is_lex_largest,
    fp2_is_zero,
    fp2_mul,
    fp2_neg,
    fp2_pow,
    fp2_scalar,
    fp2_sqr,
    fp2_sqrt,
    fp2_sub,
    fp_inv,
    fp_sqrt,
)

B1 = 4
B2 = (4, 4)  # 4 * (1 + u)

# Standard generators (from the BLS12-381 specification).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)


# ---------------------------------------------------------------------------
# G1 (affine over Fp)
# ---------------------------------------------------------------------------


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B1) % P == 0


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], (-pt[1]) % P)


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = 3 * x1 * x1 * fp_inv(2 * y1) % P
    else:
        m = (y2 - y1) * fp_inv(x2 - x1) % P
    x3 = (m * m - x1 - x2) % P
    y3 = (m * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_double(pt):
    return g1_add(pt, pt)


def g1_mul_raw(pt, k: int):
    """Scalar mul WITHOUT reducing k mod r (for cofactor clearing).

    Jacobian double-and-add: one field inversion total, vs one per affine
    add — ~100x faster for 255-bit scalars."""
    return _jac_mul(pt, k, _FP_OPS)


def g1_mul(pt, k: int):
    return g1_mul_raw(pt, k % R)


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and g1_mul_raw(pt, R) is None


# ---------------------------------------------------------------------------
# G2 (affine over Fp2)
# ---------------------------------------------------------------------------


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B2)
    return fp2_sub(fp2_sqr(y), rhs) == FP2_ZERO


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], fp2_neg(pt[1]))


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_is_zero(fp2_add(y1, y2)):
            return None
        m = fp2_mul(fp2_scalar(fp2_sqr(x1), 3), fp2_inv(fp2_scalar(y1, 2)))
    else:
        m = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sqr(m), x1), x2)
    y3 = fp2_sub(fp2_mul(m, fp2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_double(pt):
    return g2_add(pt, pt)


def g2_mul_raw(pt, k: int):
    return _jac_mul(pt, k, _FP2_OPS)


def g2_mul(pt, k: int):
    return g2_mul_raw(pt, k % R)


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and g2_mul_raw(pt, R) is None


# psi = twist o Frobenius o untwist on the M-twist: the host oracle for
# the device decompression kernel's fast subgroup check. On G2, psi acts
# as multiplication by the BLS parameter x = -X_ABS mod r. These
# constants are THE definition — charon_tpu/ops/decompress.py and the
# SSWU kernels (charon_tpu/ops/sswu.py) import them, so kernel and
# oracle can never drift apart.
PSI_CX = fp2_inv(fp2_pow(XI, (P - 1) // 3))
PSI_CY = fp2_inv(fp2_pow(XI, (P - 1) // 2))

# psi^2 collapses to a LINEAR map (no conjugation): psi(psi(x)) =
# cx * conj(cx) * x, and cx * conj(cx) = norm(cx) lands in Fp;
# cy * conj(cy) == -1 exactly. So psi^2(x, y) = (PSI2_CX * x, -y) —
# one Fp scale and a negation, which is what the device cofactor-
# clearing graph uses. Asserted against double-psi at import below.
PSI2_CX = (PSI_CX[0] * PSI_CX[0] + PSI_CX[1] * PSI_CX[1]) % P

# G1 GLV endomorphism phi(x, y) = (BETA * x, y) with BETA a nontrivial
# cube root of unity in Fp; on G1 phi acts as multiplication by
# G1_LAMBDA = X_ABS^2 - 1 (a root of lambda^2 + lambda + 1 mod r, since
# r = x^4 - x^2 + 1 for BLS curves). The 127-bit [lambda]P ladder
# replaces the 255-bit [r]P one in the device G1 subgroup check
# (ops/decompress.py imports these constants). Which of the two
# nontrivial cube roots matches G1_LAMBDA is fixed by the import-time
# assert below — drift between kernel and oracle is impossible.
# (2^((P-1)/3) is the OTHER root, i.e. lambda^2's; hence the square.)
G1_BETA = pow(2, 2 * (P - 1) // 3, P)
G1_LAMBDA = X_ABS * X_ABS - 1


def g1_phi(pt):
    if pt is None:
        return None
    return (pt[0] * G1_BETA % P, pt[1])


def g1_in_subgroup_phi(pt) -> bool:
    """Subgroup test via phi(P) == [lambda]P — equivalent to
    g1_in_subgroup for on-curve points, with a 127-bit ladder instead
    of the 255-bit [r]P one. Cross-checked in tests/test_sswu.py."""
    if pt is None:
        return True
    return g1_is_on_curve(pt) and g1_phi(pt) == g1_mul_raw(pt, G1_LAMBDA)


def g2_psi(pt):
    if pt is None:
        return None
    x, y = pt
    return (fp2_mul(fp2_conj(x), PSI_CX), fp2_mul(fp2_conj(y), PSI_CY))


def g2_psi2(pt):
    """psi applied twice, via the collapsed linear constants."""
    if pt is None:
        return None
    x, y = pt
    return (fp2_scalar(x, PSI2_CX), fp2_neg(y))


def g2_in_subgroup_psi(pt) -> bool:
    """Subgroup test via psi(P) == [x]P (Scott 2021) — equivalent to
    g2_in_subgroup for on-curve points, with a 64-bit ladder instead of
    the 255-bit [r]P one. Cross-checked in tests/test_decompress.py."""
    if pt is None:
        return True
    return g2_is_on_curve(pt) and g2_psi(pt) == g2_neg(
        g2_mul_raw(pt, X_ABS)
    )


def g2_clear_cofactor_psi(pt):
    """Fast G2 cofactor clearing (Budroni–Pintore 2017):

        h_eff * P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2(2P)

    with x the (negative) BLS parameter. Exactly equal to the RFC 9380
    [h_eff]P ladder on EVERY point of E'(Fp2) — asserted at import by
    crypto/h2c._selfcheck — but costs two 64-bit ladders instead of the
    1253-bit h_eff one (~9x fewer point ops). The host oracle for the
    device cofactor-clearing graph (ops/sswu.py)."""
    if pt is None:
        return None
    x_p = g2_neg(g2_mul_raw(pt, X_ABS))  # [x]P (x negative)
    psi_p = g2_psi(pt)
    t = g2_neg(g2_mul_raw(g2_add(x_p, psi_p), X_ABS))  # [x^2]P + [x]psi(P)
    t = g2_add(t, g2_neg(g2_add(x_p, psi_p)))  # -[x]P - psi(P)
    t = g2_add(t, g2_neg(pt))  # - P
    return g2_add(t, g2_psi2(g2_double(pt)))


# ---------------------------------------------------------------------------
# Jacobian scalar multiplication (host-speed path; affine ops above remain
# the simple correctness oracle)
# ---------------------------------------------------------------------------

# Generic field-op tables: (add, sub, mul, sqr, neg, inv, is_zero, zero)
_FP_OPS = (
    lambda a, b: (a + b) % P,
    lambda a, b: (a - b) % P,
    lambda a, b: a * b % P,
    lambda a: a * a % P,
    lambda a: (-a) % P,
    fp_inv,
    lambda a: a % P == 0,
    0,
)
_FP2_OPS = (
    fp2_add,
    fp2_sub,
    fp2_mul,
    fp2_sqr,
    fp2_neg,
    fp2_inv,
    fp2_is_zero,
    (0, 0),
)


def _jac_double(p, ops):
    add, sub, mul, sqr, neg, _, is_zero, _z = ops
    x, y, z = p
    if is_zero(z):
        return p
    a = sqr(x)
    b = sqr(y)
    c = sqr(b)
    d = sub(sub(sqr(add(x, b)), a), c)
    d = add(d, d)
    e = add(add(a, a), a)
    f = sqr(e)
    x3 = sub(f, add(d, d))
    c8 = add(add(c, c), add(c, c))
    c8 = add(c8, c8)
    y3 = sub(mul(e, sub(d, x3)), c8)
    z3 = mul(add(y, y), z)
    return (x3, y3, z3)


def _jac_add_affine(p, q, ops):
    """Jacobian p + affine q (q != infinity)."""
    add, sub, mul, sqr, neg, _, is_zero, zero = ops
    x1, y1, z1 = p
    x2, y2 = q
    if is_zero(z1):
        one = (1, 0) if isinstance(x2, tuple) else 1
        return (x2, y2, one)
    z1z1 = sqr(z1)
    u2 = mul(x2, z1z1)
    s2 = mul(mul(y2, z1), z1z1)
    if sub(u2, x1) == zero:
        if sub(s2, y1) == zero:
            return _jac_double(p, ops)
        return (zero, zero, zero)  # p + (-p) = infinity (z == 0)
    h = sub(u2, x1)
    hh = sqr(h)
    i = add(add(hh, hh), add(hh, hh))
    j = mul(h, i)
    r = sub(s2, y1)
    r = add(r, r)
    v = mul(x1, i)
    x3 = sub(sub(sqr(r), j), add(v, v))
    y1j = mul(y1, j)
    y3 = sub(mul(r, sub(v, x3)), add(y1j, y1j))
    z3 = mul(add(z1, h), add(z1, h))
    z3 = sub(sub(z3, sqr(z1)), hh)
    return (x3, y3, z3)


def _jac_mul(pt, k: int, ops):
    if pt is None or k == 0:
        return None
    add, sub, mul, sqr, neg, inv, is_zero, _ = ops
    zero = (0, 0) if isinstance(pt[0], tuple) else 0
    acc = (zero, zero, zero)  # infinity: z == 0
    for bit in bin(k)[2:]:
        acc = _jac_double(acc, ops)
        if bit == "1":
            acc = _jac_add_affine(acc, pt, ops)
    x, y, z = acc
    if is_zero(z):
        return None
    zinv = inv(z)
    zinv2 = sqr(zinv)
    return (mul(x, zinv2), mul(mul(y, zinv2), zinv))


# ---------------------------------------------------------------------------
# ZCash-format compressed serialization (the eth2 wire format)
# ---------------------------------------------------------------------------

_COMPRESSED = 0x80
_INFINITY = 0x40
_LEX_LARGEST = 0x20


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return bytes([_COMPRESSED | _INFINITY]) + bytes(47)
    x, y = pt
    flags = _COMPRESSED | (_LEX_LARGEST if y > (P - 1) // 2 else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_from_bytes(data: bytes, subgroup_check: bool = True):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMPRESSED:
        raise ValueError("uncompressed G1 not supported")
    if flags & _INFINITY:
        if any(data[1:]) or flags & _LEX_LARGEST or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = fp_sqrt((x * x * x + B1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if (y > (P - 1) // 2) != bool(flags & _LEX_LARGEST):
        y = P - y
    pt = (x, y)
    if subgroup_check and not g1_in_subgroup(pt):
        raise ValueError("G1 point not in subgroup")
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return bytes([_COMPRESSED | _INFINITY]) + bytes(95)
    (x0, x1), y = pt
    flags = _COMPRESSED | (_LEX_LARGEST if fp2_is_lex_largest(y) else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def _endo_selfcheck() -> None:
    """Import-time consistency of the single-sourced endomorphism
    constants (the kernel families in ops/decompress.py and ops/sswu.py
    import them from here — a drifted constant must fail THIS import,
    not a device batch):

      * phi(G1) == [G1_LAMBDA]G1 — the GLV pair actually corresponds
        (BETA has two nontrivial choices; only one matches LAMBDA);
      * psi^2 via the collapsed linear constants == psi applied twice;
      * psi(G2) == [x]G2 — the subgroup-check identity on the generator.
    """
    if pow(G1_BETA, 3, P) != 1 or G1_BETA == 1:
        raise AssertionError("G1_BETA is not a nontrivial cube root of unity")
    if g1_phi(G1_GEN) != g1_mul_raw(G1_GEN, G1_LAMBDA):
        raise AssertionError("G1 GLV constants inconsistent: phi != [lambda]")
    probe = g2_double(G2_GEN)
    if g2_psi2(probe) != g2_psi(g2_psi(probe)):
        raise AssertionError("PSI2 constants inconsistent with double psi")
    if g2_psi(G2_GEN) != g2_neg(g2_mul_raw(G2_GEN, X_ABS)):
        raise AssertionError("psi does not act as [x] on G2")


_endo_selfcheck()


def g2_from_bytes(data: bytes, subgroup_check: bool = True):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMPRESSED:
        raise ValueError("uncompressed G2 not supported")
    if flags & _INFINITY:
        if any(data[1:]) or flags & _LEX_LARGEST or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = fp2_sqrt(fp2_add(fp2_mul(fp2_sqr(x), x), B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    if fp2_is_lex_largest(y) != bool(flags & _LEX_LARGEST):
        y = fp2_neg(y)
    pt = (x, y)
    if subgroup_check and not g2_in_subgroup(pt):
        raise ValueError("G2 point not in subgroup")
    return pt
