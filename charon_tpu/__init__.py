"""charon_tpu — a TPU-native distributed-validator framework.

A ground-up reimplementation of the capabilities of the reference Go
implementation (Obol Charon, surveyed in SURVEY.md): n nodes jointly operate
Ethereum validators whose BLS12-381 keys are split t-of-n, coordinating duties
via QBFT consensus and a slot-scheduled pipeline, with threshold-BLS
signature verification and aggregation executed **batch-first on TPU** via
JAX (pjit/shard_map over a device mesh) instead of one-at-a-time CPU calls.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):
  cmd/      CLI entry points                 (ref: cmd/)
  app/      wiring, lifecycle, infra         (ref: app/)
  core/     duty workflow components         (ref: core/)
  dkg/      FROST distributed key generation (ref: dkg/)
  cluster/  cluster definition/lock formats  (ref: cluster/)
  p2p/      peer networking                  (ref: p2p/)
  tbls/     threshold-BLS facade w/ swappable backends (ref: tbls/)
  crypto/   pure-Python BLS12-381 reference implementation
  ops/      JAX/Pallas batched crypto kernels (the TPU hot path)
  parallel/ device-mesh sharding of the crypto batch plane
  eth2util/ eth2 signing domains, keystores, helpers (ref: eth2util/)
  testutil/ beaconmock, validatormock, simnet substrate (ref: testutil/)
"""

__version__ = "0.1.0"
