"""cluster-definition.json: the intended cluster configuration.

Mirrors ref: cluster/definition.go — operators agree on (name, validators,
threshold, fork) before DKG; each operator signs the config hash and their
ENR with their secp256k1 key as EIP-712 typed data (wallet-displayable,
ref: cluster/eip712sigs.go).
"""

from __future__ import annotations

import hashlib
import json
import uuid as uuidlib
from dataclasses import asdict, dataclass, field, replace

from charon_tpu.app import k1util

# Current definition format revision. v1.1 adds `consensus_protocol`
# (the cluster's preferred consensus protocol, seeding the runtime
# priority negotiation) to the signed config payload.
DEFINITION_VERSION = "ctpu/v1.1"

# Parse/DKG gate: documents in any of these revisions are accepted;
# anything else is rejected up-front with an actionable error
# (ref: dkg/dkg.go:108-116 gates supported cluster-definition versions,
# cluster/definition.go supportedVersions).
SUPPORTED_VERSIONS = ("ctpu/v1.0", "ctpu/v1.1")

_CONFIG_DOMAIN = b"charon-tpu/definition-config-hash"


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


@dataclass(frozen=True)
class Operator:
    """One node operator (ref: cluster/definition.go Operator)."""

    address: str  # operator identity (eth address or label)
    enr: str  # node record (charon_tpu/eth2util/enr format)
    config_signature: str = ""  # hex k1 sig over the config hash
    enr_signature: str = ""  # hex k1 sig over the ENR

    def to_json(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class ClusterDefinition:
    name: str
    num_validators: int
    threshold: int
    fork_version: str  # 0x-hex 4 bytes
    operators: tuple[Operator, ...]
    uuid: str = field(default_factory=lambda: str(uuidlib.uuid4()))
    version: str = DEFINITION_VERSION
    timestamp: str = ""
    fee_recipient_address: str = ""
    withdrawal_address: str = ""
    dkg_algorithm: str = "frost"
    creator_address: str = ""
    # v1.1+: preferred consensus protocol (empty = node default); feeds
    # the priority/infosync negotiation's proposal ordering
    consensus_protocol: str = ""

    # -- hashing ----------------------------------------------------------

    def config_payload(self) -> dict:
        """The operator-agnostic config (what everyone signs) —
        ref: definition.go config hash covers all fields except
        signatures. VERSIONED: fields added in later revisions enter the
        payload only for documents of those revisions, so hashes of old
        documents stay stable (ref: definition.go hashes per-version)."""
        out = {
            "name": self.name,
            "uuid": self.uuid,
            "version": self.version,
            "timestamp": self.timestamp,
            "num_validators": self.num_validators,
            "threshold": self.threshold,
            "fork_version": self.fork_version,
            "fee_recipient_address": self.fee_recipient_address,
            "withdrawal_address": self.withdrawal_address,
            "dkg_algorithm": self.dkg_algorithm,
            "creator_address": self.creator_address,
            "operators": [
                {"address": op.address, "enr": op.enr}
                for op in self.operators
            ],
        }
        if self.version != "ctpu/v1.0":
            out["consensus_protocol"] = self.consensus_protocol
        return out

    def config_hash(self) -> bytes:
        return hashlib.sha256(
            _CONFIG_DOMAIN + _canonical(self.config_payload())
        ).digest()

    def definition_hash(self) -> bytes:
        """Hash over everything incl. signatures (the DKG context id —
        ref: definition.go DefinitionHash)."""
        payload = self.config_payload()
        payload["signatures"] = [
            {
                "config_signature": op.config_signature,
                "enr_signature": op.enr_signature,
            }
            for op in self.operators
        ]
        return hashlib.sha256(_CONFIG_DOMAIN + _canonical(payload)).digest()

    # -- signing (EIP-712 typed data, ref: cluster/eip712sigs.go) ----------

    def _eip712_domain(self):
        from charon_tpu.eth2util.eip712 import Domain

        return Domain(name="charon-tpu", version="1.0", chain_id=1)

    def config_signature_digest(self) -> bytes:
        """EIP-712 digest over the config hash — what wallets display and
        operators sign (ref: eip712sigs.go OperatorConfigHash type)."""
        from charon_tpu.eth2util.eip712 import Field, TypedData, hash_typed_data

        return hash_typed_data(
            self._eip712_domain(),
            TypedData(
                primary_type="OperatorConfigHash",
                fields=(
                    Field("config_hash", "bytes32", self.config_hash()),
                ),
            ),
        )

    def enr_signature_digest(self, enr: str) -> bytes:
        from charon_tpu.eth2util.eip712 import Field, TypedData, hash_typed_data

        return hash_typed_data(
            self._eip712_domain(),
            TypedData(
                primary_type="ENR",
                fields=(Field("enr", "string", enr),),
            ),
        )

    def sign_operator(self, op_index: int, privkey) -> "ClusterDefinition":
        """Operator signs the EIP-712 config digest + their ENR digest."""
        op = self.operators[op_index]
        cfg_sig = k1util.sign(privkey, self.config_signature_digest())
        enr_sig = k1util.sign(privkey, self.enr_signature_digest(op.enr))
        new_op = replace(
            op,
            config_signature=cfg_sig.hex(),
            enr_signature=enr_sig.hex(),
        )
        ops = list(self.operators)
        ops[op_index] = new_op
        return replace(self, operators=tuple(ops))

    def verify_signatures(self, pubkeys: list[bytes]) -> None:
        """pubkeys: 33-byte compressed k1 key per operator."""
        if len(pubkeys) != len(self.operators):
            raise ValueError("pubkey count mismatch")
        cfg_digest = self.config_signature_digest()
        for op, pk in zip(self.operators, pubkeys):
            if not op.config_signature or not op.enr_signature:
                raise ValueError(f"operator {op.address} has not signed")
            if not k1util.verify_bytes(
                pk, cfg_digest, bytes.fromhex(op.config_signature)
            ):
                raise ValueError(f"bad config signature for {op.address}")
            if not k1util.verify_bytes(
                pk,
                self.enr_signature_digest(op.enr),
                bytes.fromhex(op.enr_signature),
            ):
                raise ValueError(f"bad ENR signature for {op.address}")

    # -- JSON round-trip --------------------------------------------------

    def to_json(self) -> dict:
        out = self.config_payload()
        out["operators"] = [op.to_json() for op in self.operators]
        out["config_hash"] = "0x" + self.config_hash().hex()
        out["definition_hash"] = "0x" + self.definition_hash().hex()
        return out

    @classmethod
    def from_json(cls, data: dict) -> "ClusterDefinition":
        version = data.get("version", DEFINITION_VERSION)
        if version not in SUPPORTED_VERSIONS:
            # the gate every loader (run, dkg, CLI) passes through
            # (ref: dkg/dkg.go:108-116)
            raise ValueError(
                f"unsupported cluster definition version {version!r}; "
                f"supported: {', '.join(SUPPORTED_VERSIONS)}"
            )
        ops = tuple(
            Operator(
                address=o["address"],
                enr=o["enr"],
                config_signature=o.get("config_signature", ""),
                enr_signature=o.get("enr_signature", ""),
            )
            for o in data["operators"]
        )
        defn = cls(
            name=data["name"],
            num_validators=data["num_validators"],
            threshold=data["threshold"],
            fork_version=data["fork_version"],
            operators=ops,
            uuid=data["uuid"],
            version=version,
            timestamp=data.get("timestamp", ""),
            fee_recipient_address=data.get("fee_recipient_address", ""),
            withdrawal_address=data.get("withdrawal_address", ""),
            dkg_algorithm=data.get("dkg_algorithm", "frost"),
            creator_address=data.get("creator_address", ""),
            # v1.0 documents exclude this field from the signed config
            # hash, so a value smuggled into a signed v1.0 JSON would be
            # UNAUTHENTICATED — ignore it rather than store it
            consensus_protocol=(
                data.get("consensus_protocol", "")
                if version != "ctpu/v1.0"
                else ""
            ),
        )
        if "config_hash" in data:
            want = bytes.fromhex(data["config_hash"][2:])
            if want != defn.config_hash():
                raise ValueError("config hash mismatch")
        return defn
