"""cluster-lock.json: the post-DKG cluster state.

Mirrors ref: cluster/lock.go — the definition plus the created distributed
validators (group pubkey, per-node pubshares, deposit/registration data),
sealed by a BLS aggregate signature over the lock hash (every DV group key
signs it during the ceremony, ref: dkg/exchanger.go sigLock) and per-node
secp256k1 signatures (ref: dkg/nodesigs.go).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field

from charon_tpu import tbls
from charon_tpu.app import k1util
from charon_tpu.cluster.definition import ClusterDefinition, _canonical

_LOCK_DOMAIN = b"charon-tpu/lock-hash"


@dataclass(frozen=True)
class DistributedValidator:
    """ref: cluster/lock.go DistributedValidator."""

    distributed_public_key: str  # 0x-hex 48 bytes (group pubkey)
    public_shares: tuple[str, ...]  # 0x-hex 48 bytes per node (1-based order)
    deposit_data: dict = field(default_factory=dict)
    builder_registration: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "distributed_public_key": self.distributed_public_key,
            "public_shares": list(self.public_shares),
            "deposit_data": self.deposit_data,
            "builder_registration": self.builder_registration,
        }


@dataclass(frozen=True)
class ClusterLock:
    definition: ClusterDefinition
    validators: tuple[DistributedValidator, ...]
    signature_aggregate: str = ""  # 0x-hex BLS aggregate over lock hash
    node_signatures: tuple[str, ...] = ()  # hex k1 sigs, one per operator

    def lock_hash(self) -> bytes:
        payload = {
            "definition_hash": "0x" + self.definition.definition_hash().hex(),
            "validators": [v.to_json() for v in self.validators],
        }
        return hashlib.sha256(_LOCK_DOMAIN + _canonical(payload)).digest()

    def fork_info(self):
        """The cluster's signing ForkInfo: fork version from the
        definition, genesis validators root derived from the lock hash
        (single source shared by the node runtime and the exit CLI so
        signing roots always agree)."""
        from charon_tpu.eth2util.signing import ForkInfo

        fv = bytes.fromhex(self.definition.fork_version[2:])
        return ForkInfo(
            genesis_validators_root=hashlib.sha256(
                b"gvr" + self.lock_hash()
            ).digest(),
            fork_version=fv,
            genesis_fork_version=fv,
        )

    # -- verification (ref: cluster/lock.go VerifySignatures) -------------

    def verify(self, operator_k1_pubkeys: list[bytes] | None = None) -> None:
        defn = self.definition
        if len(self.validators) != defn.num_validators:
            raise ValueError("validator count mismatch")
        n = len(defn.operators)
        for v in self.validators:
            if len(v.public_shares) != n:
                raise ValueError("pubshare count mismatch")

        # BLS aggregate: every group key signed the lock hash.
        if not self.signature_aggregate:
            raise ValueError("missing aggregate signature")
        msg = self.lock_hash()
        pubkeys = [
            bytes.fromhex(v.distributed_public_key[2:])
            for v in self.validators
        ]
        tbls.verify_aggregate(
            pubkeys, msg, bytes.fromhex(self.signature_aggregate[2:])
        )

        if operator_k1_pubkeys is not None:
            if len(self.node_signatures) != len(operator_k1_pubkeys):
                raise ValueError("node signature count mismatch")
            for i, (sig, pk) in enumerate(
                zip(self.node_signatures, operator_k1_pubkeys)
            ):
                if not k1util.verify_bytes(pk, msg, bytes.fromhex(sig)):
                    raise ValueError(f"bad node signature from operator {i}")

    # -- JSON round-trip --------------------------------------------------

    def to_json(self) -> dict:
        return {
            "cluster_definition": self.definition.to_json(),
            "distributed_validators": [v.to_json() for v in self.validators],
            "signature_aggregate": self.signature_aggregate,
            "node_signatures": list(self.node_signatures),
            "lock_hash": "0x" + self.lock_hash().hex(),
        }

    @classmethod
    def from_json(cls, data: dict) -> "ClusterLock":
        lock = cls(
            definition=ClusterDefinition.from_json(data["cluster_definition"]),
            validators=tuple(
                DistributedValidator(
                    distributed_public_key=v["distributed_public_key"],
                    public_shares=tuple(v["public_shares"]),
                    deposit_data=v.get("deposit_data", {}),
                    builder_registration=v.get("builder_registration", {}),
                )
                for v in data["distributed_validators"]
            ),
            signature_aggregate=data.get("signature_aggregate", ""),
            node_signatures=tuple(data.get("node_signatures", ())),
        )
        if "lock_hash" in data:
            if bytes.fromhex(data["lock_hash"][2:]) != lock.lock_hash():
                raise ValueError("lock hash mismatch")
        return lock

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    @classmethod
    def load(cls, path: str) -> "ClusterLock":
        with open(path) as f:
            return cls.from_json(json.load(f))
