"""Cluster configuration formats: definition, lock, keystores.

Mirrors ref: cluster/ — cluster-definition.json (operators, threshold,
fork version, signatures — ref cluster/definition.go, schema
docs/configuration.md:15-52) and cluster-lock.json (adds distributed
validators: group pubkeys, pubshares, aggregate + per-node signatures —
ref cluster/lock.go, docs/configuration.md:64-80).

Hashing: canonical-JSON sha256 (this framework's wire format is JSON
end-to-end; the reference hashes SSZ — the role of the hash, as the signed
identity of the config, is identical). Signatures: secp256k1 per operator
(k1util) and BLS aggregate over the lock hash.
"""

from charon_tpu.cluster.definition import ClusterDefinition, Operator  # noqa: F401
from charon_tpu.cluster.lock import ClusterLock, DistributedValidator  # noqa: F401
