"""Cluster manifest: a mutation-DAG over the cluster's life.

Mirrors ref: cluster/manifest — the cluster state is not a static lock
file but a chain of signed mutations materialised into the current
state (materialise.go:11):

  * legacy_lock      genesis mutation embedding the ceremony's lock
                     (mutationlegacylock.go)
  * add_validators   appends distributed validators produced by a later
                     ceremony (mutationaddvalidator.go)
  * node_approval    an operator's k1 signature over a parent mutation
                     (mutationnodeapproval.go); add_validators only takes
                     effect once EVERY operator has approved it

Each mutation commits to its parent's hash, so the file is an
append-only hash chain; `materialise()` folds it into the effective
cluster state (a ClusterLock with the combined validator set). Loaded at
startup in preference to the plain lock (ref: app/app.go:166).
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field, replace

from charon_tpu.app import k1util
from charon_tpu.cluster.definition import _canonical
from charon_tpu.cluster.lock import ClusterLock, DistributedValidator
from charon_tpu.eth2util import enr as enrlib

GENESIS_PARENT = bytes(32)

TYPE_LEGACY_LOCK = "legacy_lock"
TYPE_ADD_VALIDATORS = "add_validators"
TYPE_NODE_APPROVAL = "node_approval"


@dataclass(frozen=True)
class SignedMutation:
    parent: bytes  # parent mutation hash (32B; zero for genesis)
    type: str
    timestamp: int
    data: dict  # type-specific payload (canonical-JSON hashed)
    signer: bytes = b""  # 33B k1 pubkey for signed mutation types
    signature: bytes = b""  # 64B k1 signature

    def signing_payload(self) -> dict:
        return {
            "parent": "0x" + self.parent.hex(),
            "type": self.type,
            "timestamp": self.timestamp,
            "data": self.data,
            "signer": self.signer.hex(),
        }

    def signing_digest(self) -> bytes:
        return hashlib.sha256(
            b"charon-tpu/mutation" + _canonical(self.signing_payload())
        ).digest()

    def hash(self) -> bytes:
        payload = self.signing_payload()
        payload["signature"] = self.signature.hex()
        return hashlib.sha256(
            b"charon-tpu/mutation" + _canonical(payload)
        ).digest()

    def to_json(self) -> dict:
        out = self.signing_payload()
        out["signature"] = self.signature.hex()
        return out

    @classmethod
    def from_json(cls, data: dict) -> "SignedMutation":
        return cls(
            parent=bytes.fromhex(data["parent"][2:]),
            type=data["type"],
            timestamp=data["timestamp"],
            data=data["data"],
            signer=bytes.fromhex(data["signer"]),
            signature=bytes.fromhex(data["signature"]),
        )


def _validators_from_json(items: list[dict]) -> tuple[DistributedValidator, ...]:
    return tuple(
        DistributedValidator(
            distributed_public_key=v["distributed_public_key"],
            public_shares=tuple(v["public_shares"]),
        )
        for v in items
    )


@dataclass(frozen=True)
class Manifest:
    mutations: tuple[SignedMutation, ...]

    # -- construction -----------------------------------------------------

    @classmethod
    def genesis(cls, lock: ClusterLock) -> "Manifest":
        """legacy_lock genesis mutation (ref: mutationlegacylock.go)."""
        m = SignedMutation(
            parent=GENESIS_PARENT,
            type=TYPE_LEGACY_LOCK,
            timestamp=int(time.time()),
            data={"lock": lock.to_json()},
        )
        return cls(mutations=(m,))

    def head(self) -> bytes:
        return self.mutations[-1].hash()

    def propose_add_validators(
        self, validators: list[DistributedValidator]
    ) -> SignedMutation:
        """An unsigned add_validators mutation against the current head
        (ref: mutationaddvalidator.go)."""
        return SignedMutation(
            parent=self.head(),
            type=TYPE_ADD_VALIDATORS,
            timestamp=int(time.time()),
            data={"validators": [v.to_json() for v in validators]},
        )

    def approve(self, mutation_hash: bytes, privkey) -> SignedMutation:
        """One operator's node_approval of a pending mutation
        (ref: mutationnodeapproval.go)."""
        m = SignedMutation(
            parent=self.head(),
            type=TYPE_NODE_APPROVAL,
            timestamp=int(time.time()),
            data={"approved": "0x" + mutation_hash.hex()},
            signer=k1util.public_key_to_bytes(privkey.public_key()),
        )
        return replace(
            m, signature=k1util.sign(privkey, m.signing_digest())
        )

    def append(self, mutation: SignedMutation) -> "Manifest":
        if mutation.parent != self.head():
            raise ValueError("mutation parent does not match manifest head")
        return Manifest(mutations=self.mutations + (mutation,))

    # -- materialisation (ref: materialise.go Materialise) ----------------

    def materialise(self) -> ClusterLock:
        """Fold the chain into the effective cluster state. Verifies the
        hash chain, mutation signatures, and the all-operators approval
        rule for add_validators."""
        if not self.mutations:
            raise ValueError("empty manifest")
        first = self.mutations[0]
        if first.type != TYPE_LEGACY_LOCK or first.parent != GENESIS_PARENT:
            raise ValueError("manifest must start with a legacy_lock genesis")
        lock = ClusterLock.from_json(first.data["lock"])
        operator_pubkeys = [
            enrlib.pubkey_from_string(op.enr)
            for op in lock.definition.operators
        ]

        validators = list(lock.validators)
        # pending add_validators hash -> (validators, approvals set)
        pending: dict[bytes, tuple[list, set[bytes]]] = {}
        prev = first
        for m in self.mutations[1:]:
            if m.parent != prev.hash():
                raise ValueError("broken mutation chain")
            if m.type == TYPE_ADD_VALIDATORS:
                pending[m.hash()] = (
                    list(_validators_from_json(m.data["validators"])),
                    set(),
                )
            elif m.type == TYPE_NODE_APPROVAL:
                if m.signer not in operator_pubkeys:
                    raise ValueError("approval from non-operator")
                if not k1util.verify_bytes(
                    m.signer, m.signing_digest(), m.signature
                ):
                    raise ValueError("bad approval signature")
                target = bytes.fromhex(m.data["approved"][2:])
                if target not in pending:
                    raise ValueError("approval of unknown mutation")
                vals, approvals = pending[target]
                approvals.add(m.signer)
                if len(approvals) == len(operator_pubkeys):
                    validators.extend(vals)
                    del pending[target]
            else:
                raise ValueError(f"unknown mutation type {m.type}")
            prev = m

        return replace(lock, validators=tuple(validators))

    # -- disk -------------------------------------------------------------

    def to_json(self) -> dict:
        return {"mutations": [m.to_json() for m in self.mutations]}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    @classmethod
    def from_json(cls, data: dict) -> "Manifest":
        return cls(
            mutations=tuple(
                SignedMutation.from_json(m) for m in data["mutations"]
            )
        )

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path) as f:
            return cls.from_json(json.load(f))


def load_cluster_state(data_dir) -> ClusterLock:
    """Prefer cluster-manifest.json over cluster-lock.json
    (ref: app/app.go:166 loadClusterManifest)."""
    from pathlib import Path

    data_dir = Path(data_dir)
    manifest_path = data_dir / "cluster-manifest.json"
    if manifest_path.exists():
        return Manifest.load(str(manifest_path)).materialise()
    return ClusterLock.load(str(data_dir / "cluster-lock.json"))
