"""Simnet: a whole t-of-n cluster in one process.

Mirrors ref: testutil/integration/simnet_test.go:49-130 — N nodes with
real workflow components, a shared deterministic beacon mock, in-memory
partial-signature exchange, and validatormock VCs, asserting duty
completion via the broadcast recorder.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from charon_tpu import tbls
from charon_tpu.core.aggsigdb import new_agg_sigdb
from charon_tpu.core.bcast import Broadcaster
from charon_tpu.core.consensus import ConsensusController, EchoConsensus
from charon_tpu.core.dutydb import DutyDB
from charon_tpu.core.fetcher import Fetcher
from charon_tpu.core.inclusion import InclusionChecker
from charon_tpu.core.parsigdb import ParSigDB
from charon_tpu.core.parsigex import Eth2Verifier, MemTransport, ParSigEx
from charon_tpu.core.scheduler import Scheduler
from charon_tpu.core.sigagg import SigAgg
from charon_tpu.core.tracker import Tracker, tracking
from charon_tpu.core.types import PubKey, pubkey_from_bytes
from charon_tpu.core.validatorapi import ValidatorAPI
from charon_tpu.core.wire import tracing, wire
from charon_tpu.eth2util.signing import ForkInfo
from charon_tpu.testutil.beaconmock import BeaconMock
from charon_tpu.testutil.validatormock import ValidatorMock

SIMNET_FORK = ForkInfo(
    genesis_validators_root=b"\x42" * 32,
    fork_version=b"\x00\x00\x00\x00",
    genesis_fork_version=b"\x00\x00\x00\x00",
)


@dataclass
class SimCluster:
    n: int
    t: int
    beacon: BeaconMock
    fork: ForkInfo
    group_pubkeys: list[PubKey]
    share_keys: list[dict[PubKey, bytes]]  # per node
    pubshares_by_idx: dict[int, dict[PubKey, bytes]]
    nodes: list["SimNode"] = field(default_factory=list)
    # set when built with chaos: the shared fault-injection handles
    chaos_transport: object | None = None
    chaos_qbft: object | None = None
    partitioner: object | None = None

    # -- chaos control (no-ops without a chaos build) ---------------------

    def crash_node(self, share_idx: int) -> None:
        """Crash-stop a node mid-run: its scheduler halts and the fault
        plane black-holes its traffic in BOTH directions."""
        node = self.nodes[share_idx - 1]
        node.scheduler.stop()
        if self.partitioner is not None:
            self.partitioner.crash(share_idx)

    def restart_node(self, share_idx: int):
        """Restart a crashed node; returns the new scheduler task
        (crash-only model: same wired components, fresh tick loop)."""
        import asyncio

        if self.partitioner is not None:
            self.partitioner.restart(share_idx)
        node = self.nodes[share_idx - 1]
        node.scheduler.reset()
        return asyncio.create_task(node.scheduler.run())

    def partition(self, side_a, side_b, symmetric: bool = True) -> None:
        assert self.partitioner is not None, "build_cluster(chaos=...) first"
        self.partitioner.partition(side_a, side_b, symmetric)

    def heal(self) -> None:
        if self.partitioner is not None:
            self.partitioner.heal()

    def close(self) -> None:
        """Release per-node resources (crypto-plane pools, trace JSONL
        handles) — tracing/crypto_plane builds should call this."""
        for node in self.nodes:
            if node.crypto_plane is not None:
                node.crypto_plane.close()
            if node.tracer is not None:
                node.tracer.close()

    def trace_paths(self) -> list[str]:
        """Per-node span JSONL export paths (tracing builds with a
        trace_dir); merge with app/tracer.merge_jsonl."""
        return [
            node.tracer.jsonl_path
            for node in self.nodes
            if node.tracer is not None and node.tracer.jsonl_path
        ]

    async def apply_reshare(
        self,
        new_share_keys: dict[int, dict[PubKey, bytes]],
        new_pubshares_by_idx: dict[int, dict[PubKey, bytes]],
    ) -> None:
        """Rotate key material live, mid-duties (dkg/reshare output).

        The per-node `share_keys` dict (held by each ValidatorMock) and
        the shared `pubshares_by_idx` registry (held by every node's
        Eth2Verifier and ValidatorAPI) are mutated IN PLACE, so the
        rotation takes effect on the next signature without rebuilding
        any node — the simnet mirror of app/run.Node.apply_reshare. A
        node whose index is absent from `new_share_keys` (it left the
        cluster) keeps its old share and its partials stop verifying
        against the rotated registry. Nodes with a crypto plane re-warm
        the point caches for the new pubshares (delta only)."""
        for idx, shares in new_share_keys.items():
            self.share_keys[idx - 1].clear()
            self.share_keys[idx - 1].update(shares)
        for idx, pubs in new_pubshares_by_idx.items():
            self.pubshares_by_idx.setdefault(idx, {}).clear()
            self.pubshares_by_idx[idx].update(pubs)
        for node in self.nodes:
            plane = node.crypto_plane
            if plane is not None and hasattr(plane, "warm_caches"):
                await plane.warm_caches(
                    pubkeys=[
                        p
                        for pubs in new_pubshares_by_idx.values()
                        for p in pubs.values()
                    ]
                )

    def dump_flight(self, out_dir: str) -> list[str]:
        """Dump every node's flight-recorder ring (flightrec=True
        builds) into out_dir; returns the per-node dump paths, ready
        for app/flightrec.merge_jsonl cross-node reconstruction."""
        paths: list[str] = []
        for node in self.nodes:
            if node.flightrec is None:
                continue
            path = f"{out_dir}/node{node.share_idx}.flight.jsonl"
            node.flightrec.dump_jsonl(path, trigger="demand")
            paths.append(path)
        return paths


@dataclass
class SimNode:
    share_idx: int
    scheduler: Scheduler
    vapi: ValidatorAPI
    vmock: ValidatorMock
    dutydb: DutyDB
    parsigdb: ParSigDB
    sigagg: SigAgg
    aggsigdb: AggSigDB
    bcast: Broadcaster
    consensus: ConsensusController
    inclusion: InclusionChecker | None = None
    tracker: Tracker | None = None
    tracer: object | None = None  # app/tracer.Tracer (tracing=True builds)
    crypto_plane: object | None = None  # SlotCoalescer (crypto_plane=True)
    parsigex: ParSigEx | None = None
    # core/evidence.EvidenceRegistry — per-node Byzantine detections,
    # same wiring as production (app/run.py)
    evidence: object | None = None
    # app/flightrec.FlightRecorder — per-node post-mortem ring, same
    # hook chains as production (flightrec=True builds)
    flightrec: object | None = None


class SimHostPlane:
    """Stand-in device plane for the SlotCoalescer in observability
    simnet runs: the DECODE stage upstream is the real pure-python
    point decompression + hash-to-curve (it already rejects malformed
    encodings), while the device program itself is a wall-clock sleep —
    the same isolation bench_hostplane.SimPlane uses, so tracing tests
    run jax-free. Implements the packed two-stage API so the pipelined
    pack stage (and its span) engages. NOT a verifier: decode-valid
    lanes all pass, so only wire it where a test doesn't rely on
    signature rejection."""

    def __init__(self, t: int, device_s: float = 0.002) -> None:
        self.t = t
        self.device_s = device_s

    def verify_host(self, pks, msgs, sigs):
        import time as _time

        _time.sleep(self.device_s)
        return [True] * len(pks)

    def recombine_host(self, pubshares, msgs, partials, group_pks, indices):
        raise NotImplementedError("verify-only sim plane")

    # packed two-stage API (core/cryptoplane._plane_has_packed_api)
    def pack_verify_inputs(self, pks, msgs, sigs):
        import numpy as np

        return (np.ones(len(pks), dtype=bool),)  # live mask only

    def make_lane_rand(self, n):
        return None

    def verify_packed(self, arrays, rand, n):
        import time as _time

        _time.sleep(self.device_s)
        return [True] * n

    def pack_inputs(self, *a):
        raise NotImplementedError("verify-only sim plane")

    def make_rand(self, n):
        return None

    def recombine_packed(self, *a):
        raise NotImplementedError("verify-only sim plane")


def build_cluster(
    n: int = 4,
    t: int = 3,
    num_validators: int = 1,
    slot_duration: float = 0.2,
    slots_per_epoch: int = 8,
    genesis_time: float | None = None,
    use_qbft: bool = False,
    wire_vmock: bool = True,
    protocol_prefs: list[list[str]] | None = None,
    chaos=None,  # testutil.chaos.ChaosConfig: seeded fault injection
    tracing_on: bool = False,
    trace_dir: str | None = None,
    crypto_plane: bool = False,
    flightrec: bool = False,
) -> SimCluster:
    """Create keys and wire n in-process nodes (ref: app/app.go simnet +
    cluster/test_cluster.go generator, redesigned for asyncio).

    With `chaos`, the cluster is built on the fault-injection plane:
    chaos transports for parsig exchange and QBFT messages, a ChaosBeacon
    around the shared mock, and a Partitioner for crash/restart and
    partition/heal control (ISSUE 2 tentpole).

    With `tracing_on`, every node gets its OWN app/tracer.Tracer wired
    as a wire() option plus transport-frame trace-context propagation
    (ISSUE 4) — spans land per node as they would across real machines;
    `trace_dir` additionally exports per-node JSONL for the cross-node
    merge. `crypto_plane` routes inbound parsig verification through a
    SlotCoalescer over SimHostPlane so duty traces carry real
    decode/pack/device stage spans without jax; call cluster.close()
    when done. `flightrec` gives every node its own post-mortem ring
    with the production hook chains (evidence, round changes, duty
    outcomes, flush summaries); dump with cluster.dump_flight()."""
    impl = tbls.get_implementation()

    group_pubkeys: list[PubKey] = []
    share_keys: list[dict[PubKey, bytes]] = [dict() for _ in range(n)]
    pubshares_by_idx: dict[int, dict[PubKey, bytes]] = {
        i: {} for i in range(1, n + 1)
    }
    validators: dict[PubKey, int] = {}

    for v in range(num_validators):
        secret = impl.generate_secret_key()
        shares = impl.threshold_split(secret, n, t)
        group_pk = pubkey_from_bytes(impl.secret_to_public_key(secret))
        group_pubkeys.append(group_pk)
        validators[group_pk] = v
        for idx, share in shares.items():
            share_keys[idx - 1][group_pk] = share
            pubshares_by_idx[idx][group_pk] = impl.secret_to_public_key(share)

    import time as _time

    beacon = BeaconMock(
        validators=validators,
        genesis_time=genesis_time if genesis_time is not None else _time.time(),
        slot_duration=slot_duration,
        slots_per_epoch=slots_per_epoch,
    )

    partitioner = None
    if chaos is not None:
        from charon_tpu.testutil.chaos import ChaosBeacon, Partitioner

        partitioner = Partitioner()
        beacon = ChaosBeacon(beacon, chaos)

    cluster = SimCluster(
        n=n,
        t=t,
        beacon=beacon,
        fork=SIMNET_FORK,
        group_pubkeys=group_pubkeys,
        share_keys=share_keys,
        pubshares_by_idx=pubshares_by_idx,
        partitioner=partitioner,
    )

    if chaos is not None:
        from charon_tpu.testutil.chaos import ChaosParSigTransport

        transport = ChaosParSigTransport(chaos, partitioner)
        cluster.chaos_transport = transport
    else:
        transport = MemTransport()
    qbft_net = None
    if use_qbft:
        if chaos is not None:
            from charon_tpu.testutil.chaos import ChaosMsgNet

            qbft_net = ChaosMsgNet(chaos, partitioner)
            cluster.chaos_qbft = qbft_net
        else:
            from charon_tpu.core.consensus_qbft import MemMsgNet

            qbft_net = MemMsgNet()
    # priority negotiation fabric (opt-in: protocol_prefs per node)
    prio_fabric = None
    if protocol_prefs is not None:
        from charon_tpu.core.priority import MemPriorityFabric

        assert len(protocol_prefs) == n
        prio_fabric = MemPriorityFabric()
    for i in range(1, n + 1):
        cluster.nodes.append(
            _build_node(
                cluster,
                i,
                transport,
                slots_per_epoch,
                qbft_net,
                wire_vmock,
                prio_fabric=prio_fabric,
                protocol_prefs=(
                    protocol_prefs[i - 1] if protocol_prefs else None
                ),
                tracing_on=tracing_on,
                trace_dir=trace_dir,
                crypto_plane=crypto_plane,
                flightrec=flightrec,
            )
        )
    return cluster


def _build_node(
    cluster: SimCluster,
    share_idx: int,
    transport: MemTransport,
    spe: int,
    qbft_net=None,
    wire_vmock: bool = True,
    prio_fabric=None,
    protocol_prefs: list[str] | None = None,
    tracing_on: bool = False,
    trace_dir: str | None = None,
    crypto_plane: bool = False,
    flightrec: bool = False,
) -> SimNode:
    beacon = cluster.beacon
    fork = cluster.fork

    node_tracer = None
    if tracing_on:
        from charon_tpu.app.tracer import Tracer

        jsonl = (
            f"{trace_dir}/node{share_idx}.jsonl" if trace_dir else None
        )
        node_tracer = Tracer(jsonl_path=jsonl)

    rec = None
    if flightrec:
        from charon_tpu.app import flightrec as flightrec_mod

        rec = flightrec_mod.FlightRecorder(node=f"node{share_idx}")

    plane = None
    if crypto_plane:
        from charon_tpu.app.tracer import plane_span_bridge
        from charon_tpu.core.cryptoplane import SlotCoalescer

        plane_stats = plane_span_bridge(node_tracer)
        if rec is not None:
            plane_stats = flightrec_mod.stats_hook(rec, inner=plane_stats)
        plane = SlotCoalescer(
            SimHostPlane(cluster.t),
            window=0.005,
            decode_workers=2,
            stats_hook=plane_stats,
        )

    from charon_tpu.core.evidence import EvidenceRegistry

    evidence = EvidenceRegistry(
        hook=flightrec_mod.byzantine_hook(rec) if rec is not None else None
    )
    dutydb = DutyDB()
    parsigdb = ParSigDB(threshold=cluster.t, evidence=evidence)
    sigagg = SigAgg(
        threshold=cluster.t,
        fork=fork,
        slots_per_epoch=spe,
        evidence=evidence,
    )
    # flag-selected impl, mirroring production wiring (run.py)
    aggsigdb = new_agg_sigdb()
    bcast = Broadcaster(beacon=beacon, clock=beacon.clock())
    fetcher = Fetcher(beacon)
    if qbft_net is not None:
        from charon_tpu.core.consensus_qbft import QBFTConsensus

        qc = QBFTConsensus(
            qbft_net,
            cluster.n,
            round_timeout=0.3,
            timer="inc",
            tracer=node_tracer,
            evidence=evidence,
        )
        if rec is not None:
            qc.on_round_change = flightrec_mod.consensus_hook(rec)
        consensus = ConsensusController(qc)
        # echo stays registered as a switchable alternate so priority
        # negotiation can change the protocol mid-run
        consensus.register(EchoConsensus())
    else:
        consensus = ConsensusController(EchoConsensus())
    vapi = ValidatorAPI(
        share_idx=share_idx,
        pubshares=cluster.pubshares_by_idx[share_idx],
        fork=fork,
        slots_per_epoch=spe,
    )
    verifier = Eth2Verifier(
        fork, cluster.pubshares_by_idx, spe, plane=plane
    )
    # clock enables the deadline-aware resend when a chaos transport
    # (or a real p2p link) raises on send
    parsigex = ParSigEx(
        share_idx,
        transport,
        verifier,
        clock=beacon.clock(),
        tracer=node_tracer,
        evidence=evidence,
    )
    scheduler = Scheduler(
        beacon,
        beacon.clock(),
        beacon.validators,
        slots_per_epoch=spe,
    )

    # fetcher.fetch runs as its own deadline-bounded retried task, same
    # as production (ref: app/retry wired via core.WithAsyncRetry,
    # app/app.go:571): the proposer fetch blocks on the aggregated
    # randao, and transient BN failures (fuzzed or real) re-fetch until
    # the duty deadline.
    from charon_tpu.app.retry import Retryer, with_async_retry

    clock = beacon.clock()
    retryer = Retryer(
        deadline_of=clock.duty_deadline,
        backoff=max(0.05, beacon.slot_duration / 8),
    )
    spawn_fetch = with_async_retry(retryer)

    # same tracker wiring as production (app/run.py): every edge feeds
    # step/participation events; tests expire duties to get reports.
    # threshold comes from the CLUSTER definition, not the quorum
    # default — participation accounting must agree with parsigdb/sigagg
    # about how many partials a validator needs (VERDICT weak #1).
    tracker = Tracker(
        peer_share_indices=list(range(1, cluster.n + 1)),
        threshold=cluster.t,
    )
    if rec is not None:
        tracker.subscribe(flightrec_mod.duty_hook(rec))

    options = [tracking(tracker), spawn_fetch]
    if node_tracer is not None:
        # same wire option as production (app/run.py): duty-rooted span
        # per workflow edge, recorded into THIS node's tracer
        options.insert(0, tracing(node_tracer))
    wire(
        scheduler=scheduler,
        fetcher=fetcher,
        consensus=consensus,
        dutydb=dutydb,
        validatorapi=vapi,
        parsigdb=parsigdb,
        parsigex=parsigex,
        sigagg=sigagg,
        aggsigdb=aggsigdb,
        broadcaster=bcast,
        options=options,
    )
    # fetcher pulls the aggregated randao from aggsigdb
    fetcher.register_agg_sig_db(aggsigdb.await_)

    vmock = ValidatorMock(
        vapi=vapi,
        share_keys=cluster.share_keys[share_idx - 1],
        fork=fork,
        slots_per_epoch=spe,
    )

    # The vmock performs duties when the scheduler triggers them
    # (ref: app/vmock.go wires validatormock to scheduler duties).
    # wire_vmock=False lets tests drive duties over HTTP instead.
    async def on_duty(duty, defs):
        from charon_tpu.core.types import DutyType

        if duty.type == DutyType.ATTESTER:
            await vmock.attest(duty.slot, defs)
        elif duty.type == DutyType.PROPOSER:
            # run concurrently: proposal request blocks until consensus,
            # which needs this very VC's randao partial first
            for pubkey in defs:
                asyncio.create_task(vmock.propose(duty.slot, pubkey))

    if wire_vmock:
        scheduler.subscribe_duties(on_duty)

    # inclusion checker (ref: core/tracker/inclusion.go wiring)
    # check_lag=1: simnet runs span a handful of slots; the
    # production 6-slot reorg lag would make the checker inert here
    inclusion = InclusionChecker(beacon, check_lag=1)
    bcast.subscribe(inclusion.submitted)
    scheduler.subscribe_slots(inclusion.on_slot)

    # priority/infosync negotiation at epoch edges, switching the
    # consensus protocol to the cluster choice (same wiring as
    # app/run.py; ref: core/priority + core/infosync)
    if prio_fabric is not None and protocol_prefs is not None:
        from charon_tpu.core.priority import (
            InfoSync,
            Prioritiser,
            protocol_switcher,
        )

        prio_fabric.join()
        prioritiser = Prioritiser(
            node_idx=share_idx,
            quorum=cluster.t,
            exchange=prio_fabric.exchange,
            consensus=consensus,
            topics_fn=lambda: {InfoSync.TOPIC_PROTOCOL: protocol_prefs},
        )
        prioritiser.subscribe(protocol_switcher(consensus))
        infosync = InfoSync(prioritiser)
        scheduler.subscribe_slots(infosync.on_slot)

    return SimNode(
        share_idx=share_idx,
        scheduler=scheduler,
        vapi=vapi,
        vmock=vmock,
        dutydb=dutydb,
        parsigdb=parsigdb,
        sigagg=sigagg,
        aggsigdb=aggsigdb,
        bcast=bcast,
        consensus=consensus,
        inclusion=inclusion,
        tracker=tracker,
        tracer=node_tracer,
        crypto_plane=plane,
        parsigex=parsigex,
        evidence=evidence,
        flightrec=rec,
    )
