"""Golden-file test helpers.

Mirrors ref: testutil/golden.go:36-86 (RequireGoldenBytes/JSON + testdata/
directories + an -update flag): assertions against committed golden files
catch unintended format drift in consensus-critical serializations (lock
hashes, wire envelopes, records). A missing golden FAILS (like the Go
counterpart) — run with env UPDATE_GOLDEN=1 to (re)generate.
"""

from __future__ import annotations

import json
import os
from pathlib import Path


def _should_update() -> bool:
    return os.environ.get("UPDATE_GOLDEN", "") not in ("", "0")


def golden_path(test_file: str, name: str) -> Path:
    d = Path(test_file).resolve().parent / "testdata"
    d.mkdir(exist_ok=True)
    return d / name


def require_golden_bytes(test_file: str, name: str, data: bytes) -> None:
    path = golden_path(test_file, name)
    if _should_update():
        path.write_bytes(data)
        return
    assert path.exists(), (
        f"golden file {path} missing — run with UPDATE_GOLDEN=1 to create"
    )
    want = path.read_bytes()
    assert data == want, (
        f"golden mismatch for {name}: got {len(data)}B, want {len(want)}B "
        f"(set UPDATE_GOLDEN=1 to regenerate)"
    )


def require_golden_json(test_file: str, name: str, obj) -> None:
    data = (
        json.dumps(obj, indent=2, sort_keys=True).encode() + b"\n"
    )
    require_golden_bytes(test_file, name, data)
