"""ValidatorMock: a fake validator client driving the ValidatorAPI.

Mirrors ref: testutil/validatormock — holds this node's *share* private
keys and performs duties against the vapi: pull attestation data, sign
with the share key, submit the partial signature (ref:
testutil/validatormock/attest.go, propose.go; wired in-process by
app/vmock.go).
"""

from __future__ import annotations

from dataclasses import dataclass

from charon_tpu import tbls
from charon_tpu.core.eth2data import Attestation, SignedData
from charon_tpu.core.scheduler import DutyDefinition
from charon_tpu.core.types import PubKey
from charon_tpu.core.validatorapi import ValidatorAPI
from charon_tpu.eth2util.signing import ForkInfo


@dataclass
class ValidatorMock:
    """share_keys: group pubkey -> this node's share private key bytes."""

    vapi: ValidatorAPI
    share_keys: dict[PubKey, bytes]
    fork: ForkInfo
    slots_per_epoch: int = 32

    async def attest(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        """Perform the attester duty for all our validators in this slot
        (ref: validatormock/attest.go)."""
        atts = []
        for pubkey, d in defs.items():
            data = await self.vapi.attestation_data(slot, d.committee_index)
            bits = tuple(
                i == d.validator_committee_index
                for i in range(d.committee_length)
            )
            unsigned = Attestation(aggregation_bits=bits, data=data)
            root = SignedData("attestation", unsigned).signing_root(
                self.fork, slot // self.slots_per_epoch
            )
            sig = tbls.sign(self.share_keys[pubkey], root)
            atts.append(Attestation(bits, data, sig))
        if atts:
            await self.vapi.submit_attestations(atts)

    async def propose(self, slot: int, pubkey: PubKey) -> None:
        """Randao partial then signed proposal (ref: validatormock/propose.go)."""
        epoch = slot // self.slots_per_epoch
        randao_root = SignedData("randao", epoch).signing_root(self.fork, epoch)
        randao_sig = tbls.sign(self.share_keys[pubkey], randao_root)
        await self.vapi.submit_randao(slot, pubkey, randao_sig)

        proposal = await self.vapi.proposal(slot, pubkey)
        root = SignedData("block", proposal).signing_root(self.fork, epoch)
        sig = tbls.sign(self.share_keys[pubkey], root)
        await self.vapi.submit_proposal(pubkey, proposal, sig)
