"""ValidatorMock: a fake validator client driving the ValidatorAPI.

Mirrors ref: testutil/validatormock — holds this node's *share* private
keys and performs duties against the vapi: pull attestation data, sign
with the share key, submit the partial signature (ref:
testutil/validatormock/attest.go, propose.go; wired in-process by
app/vmock.go).
"""

from __future__ import annotations

from dataclasses import dataclass

from charon_tpu import tbls
from charon_tpu.core.eth2data import Attestation, SignedData
from charon_tpu.core.scheduler import DutyDefinition
from charon_tpu.core.types import PubKey
from charon_tpu.core.validatorapi import ValidatorAPI
from charon_tpu.eth2util.signing import ForkInfo


@dataclass
class ValidatorMock:
    """share_keys: group pubkey -> this node's share private key bytes."""

    vapi: ValidatorAPI
    share_keys: dict[PubKey, bytes]
    fork: ForkInfo
    slots_per_epoch: int = 32

    async def attest(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        """Perform the attester duty for all our validators in this slot
        (ref: validatormock/attest.go)."""
        atts = []
        for pubkey, d in defs.items():
            data = await self.vapi.attestation_data(slot, d.committee_index)
            bits = tuple(
                i == d.validator_committee_index
                for i in range(d.committee_length)
            )
            unsigned = Attestation(aggregation_bits=bits, data=data)
            root = SignedData("attestation", unsigned).signing_root(
                self.fork, slot // self.slots_per_epoch
            )
            sig = tbls.sign(self.share_keys[pubkey], root)
            atts.append(Attestation(bits, data, sig))
        if atts:
            await self.vapi.submit_attestations(atts)

    async def propose(self, slot: int, pubkey: PubKey) -> None:
        """Randao partial then signed proposal (ref: validatormock/propose.go)."""
        epoch = slot // self.slots_per_epoch
        randao_root = SignedData("randao", epoch).signing_root(self.fork, epoch)
        randao_sig = tbls.sign(self.share_keys[pubkey], randao_root)
        await self.vapi.submit_randao(slot, pubkey, randao_sig)

        proposal = await self.vapi.proposal(slot, pubkey)
        root = SignedData("block", proposal).signing_root(self.fork, epoch)
        sig = tbls.sign(self.share_keys[pubkey], root)
        await self.vapi.submit_proposal(pubkey, proposal, sig)


@dataclass
class HttpValidatorMock:
    """A fake VC that drives duties ONLY through the beacon-API HTTP
    server, covering every duty family the router serves (ref:
    testutil/validatormock drives charon's router over HTTP the same way;
    the simnet asserts completion via the broadcast recorder,
    testutil/integration/simnet_test.go:49-130).

    client: HttpVapiClient; validators: group pubkey -> index."""

    client: object
    share_keys: dict[PubKey, bytes]
    validators: dict[PubKey, int]
    fork: ForkInfo
    slots_per_epoch: int = 32

    def _sign(self, pubkey: PubKey, kind: str, payload, slot: int) -> bytes:
        root = SignedData(kind, payload).signing_root(
            self.fork, slot // self.slots_per_epoch
        )
        return tbls.sign(self.share_keys[pubkey], root)

    async def attest(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        atts = []
        for pubkey, d in defs.items():
            data = await self.client.attestation_data(slot, d.committee_index)
            bits = tuple(
                i == d.validator_committee_index
                for i in range(d.committee_length)
            )
            unsigned = Attestation(aggregation_bits=bits, data=data)
            sig = self._sign(pubkey, "attestation", unsigned, slot)
            atts.append(Attestation(bits, data, sig))
        if atts:
            await self.client.submit_attestations(atts)

    async def propose(self, slot: int, pubkey: PubKey) -> None:
        """GET v3 blocks with the randao partial as randao_reveal, then
        sign + POST the block (ref: validatormock/propose.go)."""
        epoch = slot // self.slots_per_epoch
        randao_sig = self._sign(pubkey, "randao", epoch, slot)
        proposal = await self.client.produce_block(slot, randao_sig)
        sig = self._sign(pubkey, "block", proposal, slot)
        await self.client.submit_block(proposal, sig)

    async def aggregate(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        """Selection partials -> aggregated proofs -> aggregate att ->
        signed AggregateAndProof (ref: validatormock attest.go aggregation
        + eth2exp beacon committee selections)."""
        from charon_tpu.core.eth2data import AggregateAndProof

        selections = []
        for pubkey, d in defs.items():
            proof = self._sign(pubkey, "selection_proof", slot, slot)
            selections.append((d.validator_index, slot, proof))
        aggregated = await self.client.beacon_committee_selections(selections)
        by_vidx = {vidx: proof for vidx, _, proof in aggregated}

        items = []
        for pubkey, d in defs.items():
            data = await self.client.attestation_data(slot, d.committee_index)
            agg_att = await self.client.aggregate_attestation(
                slot, data.hash_tree_root()
            )
            cap = AggregateAndProof(
                aggregator_index=d.validator_index,
                aggregate=agg_att,
                selection_proof=by_vidx[d.validator_index],
            )
            sig = self._sign(pubkey, "aggregate_and_proof", cap, slot)
            items.append((cap, sig))
        await self.client.submit_aggregate_and_proofs(items)

    async def sync_message(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        from charon_tpu.core.eth2data import SyncCommitteeMessage

        root = await self.client.head_root(slot)
        msgs = []
        for pubkey, d in defs.items():
            msg = SyncCommitteeMessage(
                slot=slot,
                beacon_block_root=root,
                validator_index=d.validator_index,
            )
            sig = self._sign(pubkey, "sync_message", msg, slot)
            msgs.append(
                SyncCommitteeMessage(slot, root, d.validator_index, sig)
            )
        await self.client.submit_sync_messages(msgs)

    async def sync_contribution(self, slot: int, defs: dict[PubKey, DutyDefinition]) -> None:
        from charon_tpu.core.eth2data import (
            ContributionAndProof,
            SyncSelectionData,
        )

        selections = []
        for pubkey, d in defs.items():
            sel = SyncSelectionData(slot, d.committee_index)
            proof = self._sign(pubkey, "sync_selection", sel, slot)
            selections.append(
                (d.validator_index, slot, d.committee_index, proof)
            )
        aggregated = await self.client.sync_committee_selections(selections)
        by_vidx = {vidx: proof for vidx, _, _, proof in aggregated}

        root = await self.client.head_root(slot)
        items = []
        for pubkey, d in defs.items():
            contrib = await self.client.sync_committee_contribution(
                slot, d.committee_index, root
            )
            cap = ContributionAndProof(
                aggregator_index=d.validator_index,
                contribution=contrib,
                selection_proof=by_vidx[d.validator_index],
            )
            sig = self._sign(pubkey, "contribution_and_proof", cap, slot)
            items.append((cap, sig))
        await self.client.submit_contribution_and_proofs(items)

    async def register(self, pubkey: PubKey, fee_recipient: bytes = b"\xfe" * 20) -> None:
        from charon_tpu.core.eth2data import ValidatorRegistration
        from charon_tpu.core.types import pubkey_to_bytes

        reg = ValidatorRegistration(
            fee_recipient=fee_recipient,
            gas_limit=30_000_000,
            timestamp=0,
            pubkey=pubkey_to_bytes(pubkey),
        )
        sig = self._sign(pubkey, "registration", reg, 0)
        await self.client.register_validators([(reg, sig)])

    async def exit(self, pubkey: PubKey, epoch: int) -> None:
        from charon_tpu.core.eth2data import VoluntaryExit

        exit_msg = VoluntaryExit(
            epoch=epoch, validator_index=self.validators[pubkey]
        )
        sig = self._sign(
            pubkey, "exit", exit_msg, epoch * self.slots_per_epoch
        )
        await self.client.submit_voluntary_exit(exit_msg, sig)
