"""Compose: multi-PROCESS cluster harness.

Mirrors ref: testutil/compose — the reference code-generates a
docker-compose.yml and smoke-tests whole clusters as separate containers
(compose/smoke/smoke_test.go). Here the same isolation comes from OS
processes: `generate()` creates the cluster on disk plus a compose.json
describing every node's command line; `ComposeCluster` launches each
node as `python -m charon_tpu.cmd.cli run ...` with real TCP p2p between
them, waits for readiness via the monitoring endpoint, and polls
Prometheus metrics to assert duty completion.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def generate(
    out_dir: str | Path,
    n: int = 4,
    threshold: int = 3,
    validators: int = 1,
    slot_duration: float = 1.0,
    slots_per_epoch: int = 8,
) -> dict:
    """create-cluster + compose.json describing every node's run command
    (ref: compose/compose.go config generation)."""
    from charon_tpu.cmd import cli

    out_dir = Path(out_dir)
    rc = cli.main(
        [
            "create-cluster",
            "--name",
            "compose",
            "--nodes",
            str(n),
            "--threshold",
            str(threshold),
            "--validators",
            str(validators),
            "--output-dir",
            str(out_dir),
        ]
    )
    if rc != 0:
        raise RuntimeError("create-cluster failed")

    p2p_ports = _free_ports(n)
    vapi_ports = _free_ports(n)
    mon_ports = _free_ports(n)
    peers = ",".join(f"127.0.0.1:{p}" for p in p2p_ports)
    genesis = time.time() + 2.0  # all nodes share one aligned genesis

    nodes = []
    for i in range(n):
        nodes.append(
            {
                "data_dir": str(out_dir / f"node{i}"),
                "node_index": i,
                "p2p_port": p2p_ports[i],
                "validator_api_port": vapi_ports[i],
                "monitoring_port": mon_ports[i],
                "argv": [
                    sys.executable,
                    "-m",
                    "charon_tpu.cmd.cli",
                    "run",
                    "--data-dir",
                    str(out_dir / f"node{i}"),
                    "--node-index",
                    str(i),
                    "--simnet",
                    "--no-tpu",
                    "--peers",
                    peers,
                    "--p2p-port",
                    str(p2p_ports[i]),
                    "--validator-api-port",
                    str(vapi_ports[i]),
                    "--monitoring-port",
                    str(mon_ports[i]),
                    "--slot-duration",
                    str(slot_duration),
                    "--slots-per-epoch",
                    str(slots_per_epoch),
                    "--genesis-time",
                    str(genesis),
                ],
            }
        )
    config = {"nodes": nodes, "genesis_time": genesis}
    (out_dir / "compose.json").write_text(json.dumps(config, indent=2))
    return config


class ComposeCluster:
    """Launch + observe + tear down the generated cluster
    (ref: compose/smoke/smoke_test.go)."""

    def __init__(self, config: dict, env: dict | None = None):
        self.config = config
        self.procs: list[subprocess.Popen] = []
        self._killed: set[int] = set()
        self.env = dict(os.environ)
        self.env["JAX_PLATFORMS"] = "cpu"
        self.env["PYTHONPATH"] = (
            str(REPO) + os.pathsep + self.env.get("PYTHONPATH", "")
        )
        self.env.update(env or {})

    def start(self) -> None:
        for node in self.config["nodes"]:
            self.procs.append(self._spawn(node, mode="w"))

    def _spawn(self, node: dict, mode: str = "a") -> subprocess.Popen:
        # per-node log files, NOT pipes: an undrained pipe blocks a chatty
        # node once the OS buffer fills and stalls the whole cluster
        log_path = Path(node["data_dir"]) / "node.log"
        node["log_path"] = str(log_path)
        log_file = open(log_path, mode)
        proc = subprocess.Popen(
            node["argv"],
            env=self.env,
            cwd=str(REPO),
            stdout=log_file,
            stderr=subprocess.STDOUT,
            text=True,
        )
        log_file.close()  # child holds its own fd
        return proc

    def kill_node(self, i: int) -> None:
        """CRASH node i (SIGKILL — no graceful shutdown, mirroring the
        crash-only recovery story: durable state is only what's on disk).
        The node is excluded from liveness checks until restarted."""
        self.procs[i].kill()
        self.procs[i].wait()
        self._killed.add(i)

    def restart_node(self, i: int) -> None:
        """Relaunch a killed node with its original command line — it
        must recover purely from its on-disk state (keystores, lock) and
        the shared genesis-time clock."""
        assert self.procs[i].poll() is not None, f"node {i} still running"
        self.procs[i] = self._spawn(self.config["nodes"][i])
        self._killed.discard(i)

    def metrics(self, i: int) -> str:
        port = self.config["nodes"][i]["monitoring_port"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=3
        ) as resp:
            return resp.read().decode()

    def metric_value(self, i: int, name: str) -> float:
        total = 0.0
        found = False
        for line in self.metrics(i).splitlines():
            if line.startswith(name):
                total += float(line.rsplit(" ", 1)[1])
                found = True
        return total if found else 0.0

    def wait_metric(
        self,
        name: str,
        minimum: float,
        timeout: float = 60.0,
        nodes: list[int] | None = None,
    ) -> None:
        """Block until each listed node's `name` metric reaches
        `minimum` (all nodes when `nodes` is None)."""
        idxs = (
            [i for i in range(len(self.config["nodes"])) if i not in self._killed]
            if nodes is None
            else nodes
        )
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if all(self.metric_value(i, name) >= minimum for i in idxs):
                    return
            except Exception:
                pass  # node still starting
            self._check_alive(idxs)
            time.sleep(0.5)
        raise TimeoutError(f"metric {name} never reached {minimum}")

    def node_log(self, i: int) -> str:
        try:
            return Path(self.config["nodes"][i]["log_path"]).read_text()
        except OSError:
            return ""

    def _check_alive(self, nodes: list[int] | None = None) -> None:
        idxs = (
            [i for i in range(len(self.procs)) if i not in self._killed]
            if nodes is None
            else nodes
        )
        for i in idxs:
            if self.procs[i].poll() is not None:
                raise RuntimeError(
                    f"node {i} exited rc={self.procs[i].returncode}:\n"
                    f"{self.node_log(i)[-4000:]}"
                )

    def stop(self) -> list[str]:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        return [self.node_log(i) for i in range(len(self.procs))]
