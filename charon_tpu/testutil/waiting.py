"""Progress-based waiting for simnet/e2e tests.

One shared watchdog instead of per-file copies: on a loaded 1-core CI
box the event loop can be starved for long stretches, so e2e waits must
demand fresh progress per window rather than raw speed across one fixed
wall-clock bound (the pattern proven by
tests/test_simnet.py::test_simnet_survives_fuzzed_beacon).
"""

from __future__ import annotations

import asyncio
import time

# every recorder list on BeaconMock that a full-duty e2e run fills
ALL_DUTY_RECORDERS = (
    "attestations",
    "proposals",
    "aggregates",
    "sync_messages",
    "contributions",
    "registrations",
    "exits",
)


async def wait_for_broadcasts(
    beacon,
    want: int = 4,
    recorders=ALL_DUTY_RECORDERS,
    first_window: float = 120.0,
    window: float = 60.0,
) -> None:
    """Wait until every named BeaconMock recorder holds >= `want`
    entries. The deadline extends whenever the outstanding count drops;
    a full window with zero fresh broadcasts raises TimeoutError."""

    def outstanding() -> int:
        return sum(
            max(0, want - len(getattr(beacon, name))) for name in recorders
        )

    deadline = time.monotonic() + first_window
    seen = outstanding()
    while outstanding() > 0:
        if outstanding() < seen:
            seen = outstanding()
            # progress only ever EXTENDS the allowance — early progress
            # inside the first window must not shrink what remains
            deadline = max(deadline, time.monotonic() + window)
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"no progress: {seen} broadcasts outstanding"
            )
        await asyncio.sleep(0.05)
