"""Strict beacon-API schema validation for the validator-API surface.

The reference proves its vapi against REAL validator clients (Teku in
testutil/integration, full clients in the compose tier); this image has
no VC binary, so the equivalent rigor comes from asserting every request
and response against the published beacon-API OpenAPI shapes
(github.com/ethereum/beacon-APIs): field presence, quoted-uint64 and
0x-hex formats, and container structure. A stock VC parses exactly these
shapes — any violation here is a bug a real client would hit.

Use: `SchemaClient` wraps the HTTP test client and validates every
exchange against the route table; `validate(schema, value, where)`
raises SchemaError with a precise JSON path on the first violation.
"""

from __future__ import annotations

import re
from typing import Any, Callable


class SchemaError(AssertionError):
    pass


def _fail(where: str, msg: str) -> None:
    raise SchemaError(f"{where}: {msg}")


# -- combinators -------------------------------------------------------------


def Uint(where: str, v: Any) -> None:
    """Quoted uint64/uint256 — the beacon API serializes ALL integers as
    decimal strings."""
    if not isinstance(v, str) or not v.isdigit():
        _fail(where, f"expected quoted uint, got {v!r}")


def Hex(length: int | None = None) -> Callable:
    def check(where: str, v: Any) -> None:
        # whole bytes only: odd nibble counts are not decodable and a
        # real VC's hex parser rejects them
        if not isinstance(v, str) or not re.fullmatch(
            r"0x(?:[0-9a-fA-F]{2})*", v
        ):
            _fail(where, f"expected 0x-hex string (whole bytes), got {v!r}")
        if length is not None and len(v) != 2 + 2 * length:
            _fail(where, f"expected {length}-byte hex, got {len(v) // 2 - 1}")

    return check


def HexVar(where: str, v: Any) -> None:
    Hex(None)(where, v)


def Bool(where: str, v: Any) -> None:
    if not isinstance(v, bool):
        _fail(where, f"expected bool, got {v!r}")


def Str(where: str, v: Any) -> None:
    if not isinstance(v, str):
        _fail(where, f"expected string, got {v!r}")


def Enum(*values: str) -> Callable:
    def check(where: str, v: Any) -> None:
        if v not in values:
            _fail(where, f"expected one of {values}, got {v!r}")

    return check


def Arr(item: Callable) -> Callable:
    def check(where: str, v: Any) -> None:
        if not isinstance(v, list):
            _fail(where, f"expected array, got {type(v).__name__}")
        for i, x in enumerate(v):
            item(f"{where}[{i}]", x)

    return check


def Obj(fields: dict[str, Callable], optional: tuple[str, ...] = ()) -> Callable:
    """Every non-optional field REQUIRED with its format; extra fields
    are allowed (the spec permits additive evolution)."""

    def check(where: str, v: Any) -> None:
        if not isinstance(v, dict):
            _fail(where, f"expected object, got {type(v).__name__}")
        for name, sub in fields.items():
            if name not in v:
                if name in optional:
                    continue
                _fail(where, f"missing required field {name!r}")
            sub(f"{where}.{name}", v[name])

    return check


def OneOf(*alts: Callable) -> Callable:
    def check(where: str, v: Any) -> None:
        errors = []
        for alt in alts:
            try:
                alt(where, v)
                return
            except SchemaError as e:
                errors.append(str(e))
        _fail(where, "no variant matched: " + " | ".join(errors))

    return check


def Data(inner: Callable, extra: dict[str, Callable] | None = None, optional: tuple[str, ...] = ()) -> Callable:
    return Obj({"data": inner, **(extra or {})}, optional=optional)


# -- consensus containers ----------------------------------------------------

CHECKPOINT = Obj({"epoch": Uint, "root": Hex(32)})
ATT_DATA = Obj(
    {
        "slot": Uint,
        "index": Uint,
        "beacon_block_root": Hex(32),
        "source": CHECKPOINT,
        "target": CHECKPOINT,
    }
)
ATTESTATION = Obj(
    {"aggregation_bits": HexVar, "data": ATT_DATA, "signature": Hex(96)}
)
ETH1_DATA = Obj(
    {"deposit_root": Hex(32), "deposit_count": Uint, "block_hash": Hex(32)}
)
SYNC_AGGREGATE = Obj(
    {"sync_committee_bits": Hex(64), "sync_committee_signature": Hex(96)}
)
_PAYLOAD_COMMON = {
    "parent_hash": Hex(32),
    "fee_recipient": Hex(20),
    "state_root": Hex(32),
    "receipts_root": Hex(32),
    "logs_bloom": Hex(256),
    "prev_randao": Hex(32),
    "block_number": Uint,
    "gas_limit": Uint,
    "gas_used": Uint,
    "timestamp": Uint,
    "extra_data": HexVar,
    "base_fee_per_gas": Uint,
    "block_hash": Hex(32),
}
WITHDRAWAL = Obj(
    {"index": Uint, "validator_index": Uint, "address": Hex(20), "amount": Uint}
)
EXECUTION_PAYLOAD_DENEB = Obj(
    {
        **_PAYLOAD_COMMON,
        "transactions": Arr(HexVar),
        "withdrawals": Arr(WITHDRAWAL),
        "blob_gas_used": Uint,
        "excess_blob_gas": Uint,
    }
)
EXECUTION_PAYLOAD_HEADER_DENEB = Obj(
    {
        **_PAYLOAD_COMMON,
        "transactions_root": Hex(32),
        "withdrawals_root": Hex(32),
        "blob_gas_used": Uint,
        "excess_blob_gas": Uint,
    }
)
_BODY_COMMON = {
    "randao_reveal": Hex(96),
    "eth1_data": ETH1_DATA,
    "graffiti": Hex(32),
    "proposer_slashings": Arr(Obj({})),
    "attester_slashings": Arr(Obj({})),
    "attestations": Arr(ATTESTATION),
    "deposits": Arr(Obj({})),
    "voluntary_exits": Arr(Obj({})),
    "sync_aggregate": SYNC_AGGREGATE,
    "bls_to_execution_changes": Arr(Obj({})),
}
BLOCK_BODY_DENEB = Obj(
    {
        **_BODY_COMMON,
        "execution_payload": EXECUTION_PAYLOAD_DENEB,
        "blob_kzg_commitments": Arr(Hex(48)),
    }
)
BLINDED_BODY_DENEB = Obj(
    {
        **_BODY_COMMON,
        "execution_payload_header": EXECUTION_PAYLOAD_HEADER_DENEB,
        "blob_kzg_commitments": Arr(Hex(48)),
    }
)


def _block(body: Callable) -> Callable:
    return Obj(
        {
            "slot": Uint,
            "proposer_index": Uint,
            "parent_root": Hex(32),
            "state_root": Hex(32),
            "body": body,
        }
    )


BLOCK_DENEB = _block(BLOCK_BODY_DENEB)
BLINDED_BLOCK_DENEB = _block(BLINDED_BODY_DENEB)
BLOCK_CONTENTS_DENEB = Obj(
    {
        "block": BLOCK_DENEB,
        "kzg_proofs": Arr(Hex(48)),
        "blobs": Arr(HexVar),
    }
)
SIGNED_BLOCK_DENEB = Obj({"message": BLOCK_DENEB, "signature": Hex(96)})
SIGNED_BLOCK_CONTENTS_DENEB = Obj(
    {
        "signed_block": SIGNED_BLOCK_DENEB,
        "kzg_proofs": Arr(Hex(48)),
        "blobs": Arr(HexVar),
    }
)
SIGNED_BLINDED_BLOCK_DENEB = Obj(
    {"message": BLINDED_BLOCK_DENEB, "signature": Hex(96)}
)

CONTRIBUTION = Obj(
    {
        "slot": Uint,
        "beacon_block_root": Hex(32),
        "subcommittee_index": Uint,
        "aggregation_bits": Hex(16),
        "signature": Hex(96),
    }
)
SYNC_MSG = Obj(
    {
        "slot": Uint,
        "beacon_block_root": Hex(32),
        "validator_index": Uint,
        "signature": Hex(96),
    }
)
REGISTRATION = Obj(
    {
        "message": Obj(
            {
                "fee_recipient": Hex(20),
                "gas_limit": Uint,
                "timestamp": Uint,
                "pubkey": Hex(48),
            }
        ),
        "signature": Hex(96),
    }
)
SIGNED_EXIT = Obj(
    {
        "message": Obj({"epoch": Uint, "validator_index": Uint}),
        "signature": Hex(96),
    }
)
AGG_AND_PROOF = Obj(
    {
        "message": Obj(
            {
                "aggregator_index": Uint,
                "aggregate": ATTESTATION,
                "selection_proof": Hex(96),
            }
        ),
        "signature": Hex(96),
    }
)
CONTRIB_AND_PROOF = Obj(
    {
        "message": Obj(
            {
                "aggregator_index": Uint,
                "contribution": CONTRIBUTION,
                "selection_proof": Hex(96),
            }
        ),
        "signature": Hex(96),
    }
)
BEACON_SELECTION = Obj(
    {"validator_index": Uint, "slot": Uint, "selection_proof": Hex(96)}
)
SYNC_SELECTION = Obj(
    {
        "validator_index": Uint,
        "slot": Uint,
        "subcommittee_index": Uint,
        "selection_proof": Hex(96),
    }
)

ATTESTER_DUTY = Obj(
    {
        "pubkey": Hex(48),
        "validator_index": Uint,
        "committee_index": Uint,
        "committee_length": Uint,
        "committees_at_slot": Uint,
        "validator_committee_index": Uint,
        "slot": Uint,
    }
)
PROPOSER_DUTY = Obj(
    {"pubkey": Hex(48), "validator_index": Uint, "slot": Uint}
)
SYNC_DUTY = Obj(
    {
        "pubkey": Hex(48),
        "validator_index": Uint,
        "validator_sync_committee_indices": Arr(Uint),
    }
)
VALIDATOR_RESP = Obj(
    {
        "index": Uint,
        "balance": Uint,
        "status": Str,
        "validator": Obj(
            {
                "pubkey": Hex(48),
                "withdrawal_credentials": Hex(32),
                "effective_balance": Uint,
                "slashed": Bool,
                "activation_eligibility_epoch": Uint,
                "activation_epoch": Uint,
                "exit_epoch": Uint,
                "withdrawable_epoch": Uint,
            }
        ),
    }
)

PRODUCE_BLOCK_V3 = Obj(
    {
        "version": Enum("phase0", "altair", "bellatrix", "capella", "deneb", "electra"),
        "execution_payload_blinded": Bool,
        "execution_payload_value": Uint,
        "consensus_block_value": Uint,
        "data": OneOf(BLOCK_CONTENTS_DENEB, BLINDED_BLOCK_DENEB, BLOCK_DENEB),
    }
)

# -- route table -------------------------------------------------------------
# (method, path regex) -> (request schema | None, response schema | None)

ROUTES: list[tuple[str, str, Callable | None, Callable | None]] = [
    (
        "GET",
        r"/eth/v1/validator/attestation_data",
        None,
        Data(ATT_DATA),
    ),
    ("POST", r"/eth/v[12]/beacon/pool/attestations", Arr(ATTESTATION), None),
    ("GET", r"/eth/v3/validator/blocks/\d+", None, PRODUCE_BLOCK_V3),
    (
        "POST",
        r"/eth/v[12]/beacon/blocks",
        OneOf(SIGNED_BLOCK_CONTENTS_DENEB, SIGNED_BLOCK_DENEB),
        None,
    ),
    (
        "POST",
        r"/eth/v[12]/beacon/blinded_blocks",
        SIGNED_BLINDED_BLOCK_DENEB,
        None,
    ),
    (
        "POST",
        r"/eth/v1/validator/beacon_committee_selections",
        Arr(BEACON_SELECTION),
        Data(Arr(BEACON_SELECTION)),
    ),
    (
        "GET",
        r"/eth/v[12]/validator/aggregate_attestation",
        None,
        Data(ATTESTATION),
    ),
    (
        "POST",
        r"/eth/v[12]/validator/aggregate_and_proofs",
        Arr(AGG_AND_PROOF),
        None,
    ),
    ("POST", r"/eth/v1/beacon/pool/sync_committees", Arr(SYNC_MSG), None),
    (
        "POST",
        r"/eth/v1/validator/sync_committee_selections",
        Arr(SYNC_SELECTION),
        Data(Arr(SYNC_SELECTION)),
    ),
    (
        "GET",
        r"/eth/v1/validator/sync_committee_contribution",
        None,
        Data(CONTRIBUTION),
    ),
    (
        "POST",
        r"/eth/v1/validator/contribution_and_proofs",
        Arr(CONTRIB_AND_PROOF),
        None,
    ),
    (
        "POST",
        r"/eth/v1/validator/register_validator",
        Arr(REGISTRATION),
        None,
    ),
    ("POST", r"/eth/v1/beacon/pool/voluntary_exits", SIGNED_EXIT, None),
    (
        "POST",
        r"/eth/v1/validator/duties/attester/\d+",
        Arr(Uint),
        Data(
            Arr(ATTESTER_DUTY),
            extra={"dependent_root": Hex(32)},
            optional=("dependent_root",),
        ),
    ),
    (
        "GET",
        r"/eth/v1/validator/duties/proposer/\d+",
        None,
        Data(
            Arr(PROPOSER_DUTY),
            extra={"dependent_root": Hex(32)},
            optional=("dependent_root",),
        ),
    ),
    (
        "POST",
        r"/eth/v1/validator/duties/sync/\d+",
        Arr(Uint),
        Data(Arr(SYNC_DUTY)),
    ),
    (
        "GET",
        r"/eth/v1/beacon/states/[^/]+/validators/[^/]+",
        None,
        Data(VALIDATOR_RESP),
    ),
    (
        "GET",
        r"/eth/v1/beacon/states/[^/]+/validators",
        None,
        Data(Arr(VALIDATOR_RESP)),
    ),
    (
        "POST",
        r"/eth/v1/beacon/states/[^/]+/validators",
        None,
        Data(Arr(VALIDATOR_RESP)),
    ),
    (
        "GET",
        r"/eth/v1/beacon/blocks/head/root",
        None,
        Data(Obj({"root": Hex(32)})),
    ),
    ("GET", r"/eth/v1/node/version", None, Data(Obj({"version": Str}))),
    (
        "GET",
        r"/eth/v1/node/syncing",
        None,
        Data(
            Obj(
                {
                    "head_slot": Uint,
                    "sync_distance": Uint,
                    "is_syncing": Bool,
                },
            )
        ),
    ),
    (
        "GET",
        r"/eth/v1/beacon/genesis",
        None,
        Data(
            Obj(
                {
                    "genesis_time": Uint,
                    "genesis_validators_root": Hex(32),
                    "genesis_fork_version": Hex(4),
                }
            )
        ),
    ),
    (
        "GET",
        r"/eth/v1/beacon/states/[^/]+/fork",
        None,
        Data(
            Obj(
                {
                    "previous_version": Hex(4),
                    "current_version": Hex(4),
                    "epoch": Uint,
                }
            )
        ),
    ),
]


def find_route(method: str, path: str):
    for m, pattern, req, resp in ROUTES:
        if m == method and re.fullmatch(pattern, path):
            return req, resp
    return None


def validate(schema: Callable, value: Any, where: str) -> None:
    schema(where, value)
