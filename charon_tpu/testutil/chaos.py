"""Seeded fault-injection plane for the three trust boundaries.

A distributed validator earns its keep when `n - t` nodes, beacon
endpoints, or crypto backends misbehave, so failure modes must be
*injectable on demand and reproducible by seed* (Handel-style adversarial
schedules, PAPERS.md; ref: the upstream project covers this with
p2p/fuzz.go + testutil/beaconmock/beaconmock_fuzz.go + compose chaos
runs). This module is the one home for all of it:

  * **p2p / partial-signature transport** — drop, delay, duplicate,
    reorder and corrupt frames, asymmetric partitions, node crash and
    restart (`ChaosParSigTransport`, `ChaosMsgNet`, `chaos_p2p_node`,
    `blast_garbage`, `fuzz_node`). The old `p2p/fuzz.py` stub is gone —
    this module is the only home.
  * **beacon clients** — injected timeouts, 5xx error bursts, slow
    responses and stale-head data (`ChaosBeacon`), fed through the same
    duck-typed surface as `app/eth2wrap.MultiClient`.
  * **crypto plane** — forced backend errors (`FlakyBackend`) so the
    tbls degradation ladder (`tbls/resilient.ResilientImpl`) and the
    cryptoplane host fallback are exercised, not just trusted.

Every injector draws from its own deterministic substream of one cluster
seed (`ChaosConfig.seed`), so a failing schedule replays exactly from the
seed alone. Production code never imports this module on the default
path: `app/faultinject.py` gates construction behind an env/flag and the
un-instrumented path constructs no wrapper objects at all.
"""

from __future__ import annotations

import asyncio
import random
import time as _time_mod
from dataclasses import dataclass, field, replace as _dc_replace


@dataclass
class ChaosConfig:
    """Fault rates per boundary. All probabilities are per frame/call in
    [0, 1]; zero disables that fault. One seed drives every injector —
    substreams are derived per (seed, label) so injectors never perturb
    each other's schedules."""

    seed: int = 0

    # -- transport frame faults (per delivery) ---------------------------
    drop: float = 0.0  # frame vanishes (sender sees an error)
    silent_drop: float = 0.0  # frame vanishes without any signal
    duplicate: float = 0.0  # frame delivered twice
    reorder: float = 0.0  # frame delivered late (later frames overtake)
    corrupt: float = 0.0  # frame delivered with a mangled signature
    delay: float = 0.0  # frame delivered after a random pause
    delay_max: float = 0.05  # upper bound (s) for reorder/delay pauses

    # -- beacon client faults (per call) ---------------------------------
    bn_error: float = 0.0  # start a 5xx burst
    bn_burst_max: int = 3  # burst length in calls, 1..bn_burst_max
    bn_timeout: float = 0.0  # call times out
    bn_slow: float = 0.0  # call succeeds after bn_slow_secs
    bn_slow_secs: float = 0.3
    bn_stale_head: float = 0.0  # attestation data votes for the old head

    # -- crypto backend faults (per op) ----------------------------------
    crypto_fail_rate: float = 0.0  # probability an op raises
    crypto_fail_after: int | None = None  # ops succeed until this count

    def stream(self, label: str) -> random.Random:
        """Deterministic per-injector substream: same seed + label ->
        same schedule, regardless of what other injectors consumed."""
        return random.Random(f"chaos:{self.seed}:{label}")


_SPEC_FIELDS = {f.name for f in ChaosConfig.__dataclass_fields__.values()}


def config_from_spec(spec: str) -> ChaosConfig:
    """Parse 'seed=42,drop=0.1,bn_error=0.2' into a ChaosConfig.
    Unknown keys raise ValueError (fail fast: a typo'd fault spec that
    silently injects nothing would void the whole chaos run)."""
    cfg = ChaosConfig()
    for part in spec.split(","):
        part = part.strip()
        if not part or part in ("1", "on", "true"):
            continue  # bare enable: all-zero rates, wrappers installed
        key, _, raw = part.partition("=")
        key = key.strip()
        if key not in _SPEC_FIELDS:
            raise ValueError(
                f"unknown fault-injection key {key!r}; known: "
                + ", ".join(sorted(_SPEC_FIELDS))
            )
        current = getattr(cfg, key)
        value: object
        if key == "crypto_fail_after":
            value = int(raw)
        elif isinstance(current, int) and not isinstance(current, bool):
            value = int(raw)
        else:
            value = float(raw)
        setattr(cfg, key, value)
    return cfg


class Partitioner:
    """Asymmetric partition state shared by the transports: an ordered
    pair (src, dst) being blocked does NOT imply (dst, src) is. Crashed
    nodes neither send nor receive until restarted."""

    def __init__(self) -> None:
        self._blocked: set[tuple[int, int]] = set()
        self.crashed: set[int] = set()

    def block(self, src: int, dst: int) -> None:
        self._blocked.add((src, dst))

    def partition(self, side_a, side_b, symmetric: bool = True) -> None:
        """Sever traffic from side_a to side_b (both directions when
        symmetric), e.g. partition({1,2,3}, {4}) isolates node 4."""
        for a in side_a:
            for b in side_b:
                self._blocked.add((a, b))
                if symmetric:
                    self._blocked.add((b, a))

    def isolate(self, idx: int, peers) -> None:
        self.partition([idx], [p for p in peers if p != idx])

    def heal(self) -> None:
        self._blocked.clear()

    def crash(self, idx: int) -> None:
        self.crashed.add(idx)

    def restart(self, idx: int) -> None:
        self.crashed.discard(idx)

    def blocked(self, src: int, dst: int) -> bool:
        return (src, dst) in self._blocked


def _corrupt_parsig(psig, rng: random.Random):
    """A shape-valid copy of a ParSignedData whose signature is garbage:
    receivers must *reject* it (verifier) without crashing — mangling the
    container itself would only exercise the codec, not the crypto gate."""
    from charon_tpu.core.eth2data import ParSignedData

    return ParSignedData(
        data=psig.data.with_signature(rng.randbytes(96)),
        share_idx=psig.share_idx,
    )


class ChaosParSigTransport:
    """Drop-in for `core.parsigex.MemTransport` with seeded frame faults.

    Deliveries run as their own tasks (unlike MemTransport's serial
    awaits) so an injected delay on one destination cannot stall the
    fan-out — and so a receiver's long retry chain cannot block the
    sender, which is exactly the coupling real networks do not have.

    A delivery dropped by `drop` (or aimed at a crashed peer) raises
    ConnectionError after the healthy deliveries are dispatched, so the
    sender's deadline-aware retry re-sends; the receivers dedup by share
    index. `silent_drop` and partitions vanish frames without a signal,
    as real packet loss does.
    """

    def __init__(
        self, cfg: ChaosConfig, partitioner: Partitioner | None = None
    ) -> None:
        self.cfg = cfg
        self.part = partitioner or Partitioner()
        self.nodes: list = []
        self._rng = cfg.stream("parsig")
        self._tasks: set[asyncio.Task] = set()
        # observability: scenario tests assert faults actually fired
        self.dropped = 0
        self.silently_dropped = 0
        self.duplicated = 0
        self.delayed = 0
        self.corrupted = 0
        self.blocked = 0

    def attach(self, node) -> None:
        self.nodes.append(node)

    # -- control handles used by scenarios --------------------------------

    def crash(self, share_idx: int) -> None:
        self.part.crash(share_idx)

    def restart(self, share_idx: int) -> None:
        self.part.restart(share_idx)

    async def send(
        self, from_idx: int, duty, signed_set, tctx: str | None = None
    ) -> None:
        if from_idx in self.part.crashed:
            raise ConnectionError(f"chaos: node {from_idx} is crashed")
        failed: list[int] = []
        for node in self.nodes:
            dst = node.share_idx
            if dst == from_idx:
                continue
            if dst in self.part.crashed:
                failed.append(dst)
                continue
            if self.part.blocked(from_idx, dst):
                self.blocked += 1
                continue  # partition: silent, like real packet loss
            roll = self._rng.random()
            if roll < self.cfg.silent_drop:
                self.silently_dropped += 1
                continue
            if roll < self.cfg.silent_drop + self.cfg.drop:
                self.dropped += 1
                failed.append(dst)
                continue
            payload = signed_set
            frame_tctx = tctx
            if self._rng.random() < self.cfg.corrupt:
                self.corrupted += 1
                payload = {
                    pk: _corrupt_parsig(ps, self._rng)
                    for pk, ps in signed_set.items()
                }
                # corruption hits the whole frame: the propagated trace
                # context arrives as garbage too — receivers must fall
                # back to a fresh duty-rooted span, never crash
                frame_tctx = self._rng.randbytes(12).hex() + "-zz"
            self._deliver(node, duty, payload, frame_tctx, from_idx)
            if self._rng.random() < self.cfg.duplicate:
                self.duplicated += 1
                self._deliver(node, duty, payload, frame_tctx, from_idx)
        if failed:
            raise ConnectionError(
                f"chaos: delivery to peers {failed} failed"
            )

    def _deliver(
        self, node, duty, signed_set, tctx=None, sender=None
    ) -> None:
        async def run():
            # simulated network boundary: the delivery task inherits the
            # sender's contextvars — detach so trace context propagates
            # only via the frame's tctx (app/tracer.detached)
            from charon_tpu.app.tracer import detached

            roll = self._rng.random()
            if roll < self.cfg.reorder + self.cfg.delay:
                self.delayed += 1
                await asyncio.sleep(
                    self._rng.uniform(0.0, self.cfg.delay_max)
                )
            if node.share_idx in self.part.crashed:
                return  # crashed while the frame was in flight
            try:
                with detached():
                    await node.receive(
                        duty, signed_set, tctx=tctx, sender=sender
                    )
            except Exception:  # noqa: BLE001 — receiver faults stay local
                pass

        task = asyncio.create_task(run())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)


class ChaosMsgNet:
    """Seeded-lossy QBFT message fabric: drop-in for
    `core.consensus_qbft.MemMsgNet`. Message loss here is what forces
    round changes — the storm scenario drives the engine's liveness
    under sustained loss, not one lucky round."""

    def __init__(
        self, cfg: ChaosConfig, partitioner: Partitioner | None = None
    ) -> None:
        self.cfg = cfg
        self.part = partitioner or Partitioner()
        self.nodes: list = []
        self._rng = cfg.stream("qbft")
        self._tasks: set[asyncio.Task] = set()
        self.dropped = 0
        self.delayed = 0

    def attach(self, node) -> int:
        self.nodes.append(node)
        return len(self.nodes) - 1

    async def broadcast(
        self, from_idx: int, duty, msg, values, tctx: str | None = None
    ) -> None:
        if from_idx in self.part.crashed:
            return
        for node in self.nodes:
            if node.node_idx == from_idx:
                continue
            if node.node_idx in self.part.crashed or self.part.blocked(
                from_idx, node.node_idx
            ):
                continue
            if self._rng.random() < self.cfg.drop + self.cfg.silent_drop:
                self.dropped += 1
                continue
            if self._rng.random() < self.cfg.reorder + self.cfg.delay:
                self.delayed += 1
                self._late(node, duty, msg, values, tctx, from_idx)
                continue
            from charon_tpu.app.tracer import detached

            with detached():
                node.deliver(duty, msg, values, tctx=tctx, sender=from_idx)

    def _late(
        self, node, duty, msg, values, tctx=None, sender=None
    ) -> None:
        async def run():
            from charon_tpu.app.tracer import detached

            await asyncio.sleep(self._rng.uniform(0.0, self.cfg.delay_max))
            if node.node_idx not in self.part.crashed:
                with detached():
                    node.deliver(
                        duty, msg, values, tctx=tctx, sender=sender
                    )

        task = asyncio.create_task(run())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)


_BEACON_FAULTY_METHODS = frozenset(
    {
        "await_synced",
        "attester_duties",
        "proposer_duties",
        "sync_duties",
        "attestation_data",
        "aggregate_attestation",
        "block_proposal",
        "sync_committee_block_root",
        "sync_contribution",
        "block_attestations",
        "block_root",
        "submit_attestation",
        "submit_aggregate",
        "submit_sync_message",
        "submit_contribution",
        "submit_proposal",
        "submit_registration",
        "submit_exit",
    }
)


class ChaosBeacon:
    """Fault-injecting wrapper around any beacon client (BeaconMock or an
    HTTP client): seeded timeouts, 5xx bursts (errors arrive in runs, as
    real outages do), slow responses, and stale-head attestation data.
    Everything else — recorder lists, `clock()`, overrides — delegates to
    the wrapped client untouched, so tests keep asserting on the inner
    mock."""

    def __init__(self, inner, cfg: ChaosConfig) -> None:
        self._inner = inner
        self._cfg = cfg
        self._rng = cfg.stream("beacon")
        self._burst_left = 0
        self.injected_errors = 0
        self.injected_timeouts = 0
        self.injected_slow = 0
        self.injected_stale = 0

    def _fault(self, name: str) -> str | None:
        cfg = self._cfg
        if self._burst_left > 0:
            self._burst_left -= 1
            return "error"
        roll = self._rng.random()
        if roll < cfg.bn_error:
            self._burst_left = self._rng.randint(1, max(1, cfg.bn_burst_max)) - 1
            return "error"
        roll = self._rng.random()
        if roll < cfg.bn_timeout:
            return "timeout"
        if roll < cfg.bn_timeout + cfg.bn_slow:
            return "slow"
        if (
            name == "attestation_data"
            and self._rng.random() < cfg.bn_stale_head
        ):
            return "stale"
        return None

    def __getattr__(self, name: str):
        inner = getattr(self._inner, name)
        if name not in _BEACON_FAULTY_METHODS or not callable(inner):
            return inner

        async def call(*args, **kwargs):
            mode = self._fault(name)
            if mode == "error":
                self.injected_errors += 1
                raise ConnectionError(
                    f"chaos: injected beacon 5xx on {name}"
                )
            if mode == "timeout":
                self.injected_timeouts += 1
                raise asyncio.TimeoutError(
                    f"chaos: injected beacon timeout on {name}"
                )
            if mode == "slow":
                self.injected_slow += 1
                await asyncio.sleep(self._cfg.bn_slow_secs)
            result = await inner(*args, **kwargs)
            if mode == "stale":
                # the BN has not seen the new head yet: shape-valid data
                # voting for the previous slot's block — the pipeline
                # must still reach consensus and sign it
                self.injected_stale += 1
                prev = getattr(self._inner, "_root", None)
                if prev is not None and hasattr(result, "beacon_block_root"):
                    slot = getattr(result, "slot", args[0] if args else 1)
                    result = _dc_replace(
                        result,
                        beacon_block_root=prev("block", max(0, slot - 1)),
                    )
            return result

        return call


class FlakyBackend:
    """Forced crypto-backend errors around any tbls Implementation:
    `fail_after=N` makes every op past the N-th raise (a device that
    wedges and stays wedged); `fail_rate` raises probabilistically
    (intermittent device). Raises RuntimeError — NOT TblsError — because
    a backend fault is infrastructure, not a crypto verdict, and the
    degradation ladder must distinguish the two."""

    def __init__(
        self,
        inner,
        cfg: ChaosConfig | None = None,
        fail_rate: float | None = None,
        fail_after: int | None = None,
        seed: int = 0,
    ) -> None:
        cfg = cfg or ChaosConfig(seed=seed)
        self._inner = inner
        self._rng = cfg.stream("crypto")
        self._fail_rate = (
            cfg.crypto_fail_rate if fail_rate is None else fail_rate
        )
        self._fail_after = (
            cfg.crypto_fail_after if fail_after is None else fail_after
        )
        self.calls = 0
        self.injected_failures = 0

    def _maybe_fail(self, name: str) -> None:
        self.calls += 1
        if self._fail_after is not None and self.calls > self._fail_after:
            self.injected_failures += 1
            raise RuntimeError(
                f"chaos: crypto backend lost (op {name}, call {self.calls})"
            )
        if self._fail_rate and self._rng.random() < self._fail_rate:
            self.injected_failures += 1
            raise RuntimeError(f"chaos: injected crypto fault on {name}")

    def __getattr__(self, name: str):
        inner = getattr(self._inner, name)
        if not callable(inner) or name.startswith("_"):
            return inner

        def call(*args, **kwargs):
            self._maybe_fail(name)
            return inner(*args, **kwargs)

        return call


# -- host clock skew ---------------------------------------------------------


class SkewedClock:
    """Steppable wall clock for chaos clock-skew scenarios: installed
    as a context manager it replaces `time.time` with real time plus a
    controllable offset, while `time.monotonic` stays untouched —
    exactly the asymmetry a real host clock step (NTP correction, VM
    migration, operator fat-finger) produces. Code converting between
    the two bases (e.g. SlotCoalescer._arm's duty deadlines) sees the
    bases disagree mid-run, which is the bug class this injector
    exists to reproduce deterministically.

        with SkewedClock() as clock:
            ...  # wall clock normal
            clock.step(60.0)   # host clock jumps forward a minute
            ...  # wall clock now leads monotonic by 60 s
    """

    def __init__(self, offset: float = 0.0) -> None:
        self.offset = offset
        self._real = _time_mod.time

    def __call__(self) -> float:
        return self._real() + self.offset

    def step(self, seconds: float) -> None:
        """Step the wall clock by `seconds` (negative = backward)."""
        self.offset += seconds

    def __enter__(self) -> "SkewedClock":
        _time_mod.time = self
        return self

    def __exit__(self, *exc) -> None:
        _time_mod.time = self._real


# -- forged-signature floods -------------------------------------------------


def forged_signatures(n: int, rng: random.Random) -> list[bytes]:
    """n seeded 96-byte G2 'signatures' with plausible compression
    flags (compressed bit set, infinity bit clear) but garbage field
    bytes: they pass the cheap flag checks and then fail decompression
    or verification — the forged-flood payload a byzantine tenant
    pours into a shared crypto plane."""
    out = []
    for _ in range(n):
        b = bytearray(rng.randbytes(96))
        b[0] = 0x80 | (0x20 if rng.random() < 0.5 else 0) | (b[0] & 0x1F)
        out.append(bytes(b))
    return out


# -- raw p2p frame chaos (absorbs the old p2p/fuzz.py) -----------------------


def chaos_p2p_node(node, cfg: ChaosConfig) -> None:
    """Wrap a `p2p.transport.P2PNode`'s send with seeded frame faults:
    drop, duplicate, and corrupt (garbage bytes on the raw connection —
    the receiver's codec/auth layer must reject them without dropping
    the authenticated connection's healthy traffic)."""
    rng = cfg.stream(f"p2p:{node.index}")
    orig_send = node.send

    async def chaotic_send(peer_idx, protocol, msg, await_response=False):
        roll = rng.random()
        if roll < cfg.drop + cfg.silent_drop:
            if await_response:
                raise TimeoutError("chaos: dropped request frame")
            return None
        if roll < cfg.drop + cfg.silent_drop + cfg.corrupt:
            try:
                conn = await node._get_conn(peer_idx)
                from charon_tpu.p2p.transport import _write_frame

                async with conn.lock:
                    _write_frame(
                        conn.writer, rng.randbytes(rng.randrange(1, 64))
                    )
                    await conn.writer.drain()
            except Exception:  # noqa: BLE001 — chaos must not crash the node
                pass
            if await_response:
                raise TimeoutError("chaos: corrupted request frame")
            return None
        if rng.random() < cfg.duplicate:
            await orig_send(peer_idx, protocol, msg)
        if cfg.delay and rng.random() < cfg.delay:
            await asyncio.sleep(rng.uniform(0.0, cfg.delay_max))
        return await orig_send(peer_idx, protocol, msg, await_response)

    node.send = chaotic_send

    # broadcasts no longer route through send() (single-encode fan-out,
    # ISSUE 7) — inject the same per-delivery faults on that path too
    orig_bcast_one = node._broadcast_one

    async def chaotic_broadcast_one(peer_idx, protocol, req_id, msg, cache):
        roll = rng.random()
        if roll < cfg.silent_drop:
            return None
        if roll < cfg.silent_drop + cfg.drop:
            raise ConnectionError("chaos: dropped broadcast frame")
        if roll < cfg.silent_drop + cfg.drop + cfg.corrupt:
            try:
                conn = await node._get_conn(peer_idx)
                from charon_tpu.p2p.transport import _write_frame

                async with conn.lock:
                    _write_frame(
                        conn.writer, rng.randbytes(rng.randrange(1, 64))
                    )
                    await conn.writer.drain()
            except Exception:  # noqa: BLE001 — chaos must not crash
                pass
            return None
        if rng.random() < cfg.duplicate:
            await orig_bcast_one(peer_idx, protocol, req_id, msg, cache)
        if cfg.delay and rng.random() < cfg.delay:
            await asyncio.sleep(rng.uniform(0.0, cfg.delay_max))
        return await orig_bcast_one(peer_idx, protocol, req_id, msg, cache)

    node._broadcast_one = chaotic_broadcast_one


def fuzz_node(node, rate: float = 0.2, seed: int = 0) -> None:
    """Convenience wrapper (absorbed from the deleted p2p/fuzz.py):
    split one aggregate fault `rate` evenly across drop/corrupt/duplicate
    and install the seeded p2p frame chaos on `node`."""
    chaos_p2p_node(
        node,
        ChaosConfig(
            seed=seed,
            drop=rate / 3,
            corrupt=rate / 3,
            duplicate=rate / 3,
        ),
    )


# -- remote crypto-service socket chaos (ISSUE 17) ---------------------------


class ChaosServiceProxy:
    """Seeded TCP chaos proxy for the remote crypto-plane socket: sits
    between `core/cryptosvc_client.RemotePlane` and
    `core/cryptosvc_server.CryptoServiceServer` forwarding raw bytes
    with injectable faults, so the client's failover ladder is
    exercised against *socket-level* misbehavior (not just polite
    server errors):

      * `partition()` / `heal()` — live connections blackhole silently
        (frames vanish mid-stream; only the heartbeat miss can notice)
        and new dials are refused;
      * `slow_drip` — per-chunk forwarding delay (a congested or
        rate-limited path; deadline propagation must fail jobs over
        before the duty expires);
      * `corrupt` — per-chunk probability of mangled bytes, which
        desyncs the length-prefixed framing and must surface as a
        typed CodecError teardown + reconnect, never a crash;
      * `kill_connections()` — abort every proxied socket (the
        mid-flush SIGKILL stand-in when the real server object must
        survive for assertions).

    Fault state is mutable mid-run — scenarios script phases against
    one proxy instance.
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        cfg: ChaosConfig | None = None,
        host: str = "127.0.0.1",
    ) -> None:
        self.upstream = (upstream_host, upstream_port)
        self.cfg = cfg or ChaosConfig()
        self.host = host
        self.port = 0
        self._rng = self.cfg.stream("cryptosvc-proxy")
        self._server: asyncio.base_events.Server | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self._tasks: set[asyncio.Task] = set()
        self.partitioned = False
        self.slow_drip = 0.0  # seconds of added delay per chunk
        self.corrupt = 0.0  # per-chunk corruption probability
        # observability: scenarios assert the faults actually fired
        self.chunks = 0
        self.corrupted = 0
        self.swallowed = 0
        self.kills = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, self.host, 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self.kill_connections()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    def partition(self) -> None:
        """Blackhole: live streams swallow bytes, new dials are cut."""
        self.partitioned = True

    def heal(self) -> None:
        self.partitioned = False

    def kill_connections(self) -> None:
        self.kills += 1
        for w in list(self._writers):
            if w.transport is not None:
                w.transport.abort()
        self._writers.clear()

    async def _accept(self, reader, writer) -> None:
        self._writers.add(writer)
        if self.partitioned:
            writer.close()
            self._writers.discard(writer)
            return
        try:
            up_reader, up_writer = await asyncio.open_connection(
                *self.upstream
            )
        except OSError:
            writer.close()
            self._writers.discard(writer)
            return
        self._writers.add(up_writer)
        for src, dst in (
            (reader, up_writer),
            (up_reader, writer),
        ):
            task = asyncio.create_task(self._pump(src, dst))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _pump(self, src, dst) -> None:
        try:
            while True:
                chunk = await src.read(65536)
                if not chunk:
                    break
                self.chunks += 1
                if self.partitioned:
                    self.swallowed += 1
                    continue  # silent blackhole, like real packet loss
                if self.slow_drip:
                    await asyncio.sleep(self.slow_drip)
                if self.corrupt and self._rng.random() < self.corrupt:
                    self.corrupted += 1
                    b = bytearray(chunk)
                    for _ in range(max(1, len(b) // 64)):
                        b[self._rng.randrange(len(b))] ^= 0xFF
                    chunk = bytes(b)
                dst.write(chunk)
                await dst.drain()
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            try:
                dst.close()
            except RuntimeError:
                pass
            self._writers.discard(dst)


async def blast_garbage(
    host: str, port: int, n_frames: int = 50, seed: int = 0
) -> None:
    """Open raw connections and write random bytes at a p2p server —
    handshake and framing must reject them without taking the node
    down (moved from p2p/fuzz.py)."""
    rng = random.Random(seed)
    for _ in range(n_frames):
        try:
            _reader, writer = await asyncio.open_connection(host, port)
            writer.write(rng.randbytes(rng.randrange(1, 256)))
            await writer.drain()
            writer.close()
        except ConnectionError:
            pass
