"""HTTP client for the ValidatorAPI router: a VC that speaks only HTTP.

Duck-type compatible with the in-process ValidatorAPI surface that
ValidatorMock drives, so the same duty flows run either in-process or over
the wire (ref: testutil/validatormock talks to charon only through the
beacon API HTTP server; simnet tests assert the HTTP path end-to-end,
testutil/integration/simnet_test.go).
"""

from __future__ import annotations

import aiohttp

from charon_tpu.core.types import PubKey
from charon_tpu.core.validatorapi import VapiError
from charon_tpu.core.eth2data import (
    proposal_from_data_json,
    signed_proposal_json,
)
from charon_tpu.core.vapi_http import (
    _att_data_from_json,
    _att_data_json,
    _attestation_from_json,
    _attestation_json,
    _bits_to_hex,
    _contribution_from_json,
    _contribution_json,
    _hex,
    _unhex,
)


class HttpVapiClient:
    """Each method performs one beacon-API HTTP call against the router."""

    def __init__(self, base_url: str, validators: dict[PubKey, int]) -> None:
        self.base = base_url.rstrip("/")
        self.validators = validators
        self._session: aiohttp.ClientSession | None = None

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    async def _get(self, path: str, params=None) -> dict:
        s = await self._sess()
        async with s.get(self.base + path, params=params) as resp:
            if resp.status >= 400:
                raise VapiError(f"GET {path}: {resp.status} {await resp.text()}")
            return await resp.json()

    async def _post(self, path: str, payload, headers=None) -> dict | None:
        s = await self._sess()
        async with s.post(
            self.base + path, json=payload, headers=headers
        ) as resp:
            if resp.status >= 400:
                raise VapiError(f"POST {path}: {resp.status} {await resp.text()}")
            if resp.content_type == "application/json":
                return await resp.json()
            return None

    # -- attester ----------------------------------------------------------

    async def attestation_data(self, slot: int, committee_index: int):
        j = await self._get(
            "/eth/v1/validator/attestation_data",
            params={"slot": str(slot), "committee_index": str(committee_index)},
        )
        return _att_data_from_json(j["data"])

    async def submit_attestations(self, atts) -> None:
        await self._post(
            "/eth/v1/beacon/pool/attestations",
            [_attestation_json(a) for a in atts],
        )

    # -- proposer ----------------------------------------------------------

    async def produce_block(self, slot: int, randao_reveal: bytes):
        j = await self._get(
            f"/eth/v3/validator/blocks/{slot}",
            params={"randao_reveal": _hex(randao_reveal)},
        )
        blinded = str(j.get("execution_payload_blinded", False)).lower() in (
            "true",
            "1",
        )
        return proposal_from_data_json(j["version"], blinded, j["data"])

    async def submit_block(self, proposal, signature: bytes) -> None:
        path = (
            "/eth/v2/beacon/blinded_blocks"
            if proposal.blinded
            else "/eth/v2/beacon/blocks"
        )
        await self._post(
            path,
            signed_proposal_json(proposal, signature),
            headers={"Eth-Consensus-Version": proposal.version},
        )

    # -- aggregator --------------------------------------------------------

    async def beacon_committee_selections(self, selections):
        """selections: list of (validator_index, slot, partial_proof).
        Returns list of (validator_index, slot, aggregated_proof)."""
        j = await self._post(
            "/eth/v1/validator/beacon_committee_selections",
            [
                {
                    "validator_index": str(vidx),
                    "slot": str(slot),
                    "selection_proof": _hex(proof),
                }
                for vidx, slot, proof in selections
            ],
        )
        return [
            (int(s["validator_index"]), int(s["slot"]), _unhex(s["selection_proof"]))
            for s in j["data"]
        ]

    async def aggregate_attestation(self, slot: int, att_data_root: bytes):
        j = await self._get(
            "/eth/v1/validator/aggregate_attestation",
            params={
                "slot": str(slot),
                "attestation_data_root": _hex(att_data_root),
            },
        )
        return _attestation_from_json(j["data"])

    async def submit_aggregate_and_proofs(self, items) -> None:
        """items: list of (AggregateAndProof, signature)."""
        await self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [
                {
                    "message": {
                        "aggregator_index": str(agg.aggregator_index),
                        "aggregate": _attestation_json(agg.aggregate),
                        "selection_proof": _hex(agg.selection_proof),
                    },
                    "signature": _hex(sig),
                }
                for agg, sig in items
            ],
        )

    # -- sync committee ----------------------------------------------------

    async def submit_sync_messages(self, msgs) -> None:
        await self._post(
            "/eth/v1/beacon/pool/sync_committees",
            [
                {
                    "slot": str(m.slot),
                    "beacon_block_root": _hex(m.beacon_block_root),
                    "validator_index": str(m.validator_index),
                    "signature": _hex(m.signature),
                }
                for m in msgs
            ],
        )

    async def sync_committee_selections(self, selections):
        """selections: list of (validator_index, slot, subcommittee_index,
        partial_proof) -> aggregated."""
        j = await self._post(
            "/eth/v1/validator/sync_committee_selections",
            [
                {
                    "validator_index": str(vidx),
                    "slot": str(slot),
                    "subcommittee_index": str(sub),
                    "selection_proof": _hex(proof),
                }
                for vidx, slot, sub, proof in selections
            ],
        )
        return [
            (
                int(s["validator_index"]),
                int(s["slot"]),
                int(s["subcommittee_index"]),
                _unhex(s["selection_proof"]),
            )
            for s in j["data"]
        ]

    async def sync_committee_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: bytes
    ):
        j = await self._get(
            "/eth/v1/validator/sync_committee_contribution",
            params={
                "slot": str(slot),
                "subcommittee_index": str(subcommittee_index),
                "beacon_block_root": _hex(beacon_block_root),
            },
        )
        return _contribution_from_json(j["data"])

    async def submit_contribution_and_proofs(self, items) -> None:
        await self._post(
            "/eth/v1/validator/contribution_and_proofs",
            [
                {
                    "message": {
                        "aggregator_index": str(cap.aggregator_index),
                        "contribution": _contribution_json(cap.contribution),
                        "selection_proof": _hex(cap.selection_proof),
                    },
                    "signature": _hex(sig),
                }
                for cap, sig in items
            ],
        )

    # -- registrations / exits --------------------------------------------

    async def register_validators(self, items) -> None:
        """items: list of (ValidatorRegistration, signature)."""
        await self._post(
            "/eth/v1/validator/register_validator",
            [
                {
                    "message": {
                        "fee_recipient": _hex(reg.fee_recipient),
                        "gas_limit": str(reg.gas_limit),
                        "timestamp": str(reg.timestamp),
                        "pubkey": _hex(reg.pubkey),
                    },
                    "signature": _hex(sig),
                }
                for reg, sig in items
            ],
        )

    async def submit_voluntary_exit(self, exit_msg, signature: bytes) -> None:
        await self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            {
                "message": {
                    "epoch": str(exit_msg.epoch),
                    "validator_index": str(exit_msg.validator_index),
                },
                "signature": _hex(signature),
            },
        )

    async def head_root(self, slot: int | None = None) -> bytes:
        params = {"slot": str(slot)} if slot is not None else None
        j = await self._get("/eth/v1/beacon/blocks/head/root", params=params)
        return _unhex(j["data"]["root"])

    # -- metadata ----------------------------------------------------------

    async def get_validators(self, ids=None):
        params = {"id": ",".join(ids)} if ids else None
        j = await self._get("/eth/v1/beacon/states/head/validators", params=params)
        return j["data"]

    async def attester_duties(self, epoch: int, indices) -> list:
        j = await self._post(
            f"/eth/v1/validator/duties/attester/{epoch}",
            [str(i) for i in indices],
        )
        return j["data"]

    async def proposer_duties(self, epoch: int) -> list:
        j = await self._get(f"/eth/v1/validator/duties/proposer/{epoch}")
        return j["data"]

    async def node_version(self) -> str:
        j = await self._get("/eth/v1/node/version")
        return j["data"]["version"]


class SchemaCheckedVapiClient(HttpVapiClient):
    """HttpVapiClient that asserts every request body and response
    against the published beacon-API OpenAPI shapes
    (testutil/schemas.py). A violation raises SchemaError mid-duty, so
    any flow completed under this client is schema-conformant — the
    in-repo stand-in for the reference's real-VC integration tier
    (ref: testutil/integration runs Teku against charon's vapi)."""

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self.checked = 0
        self.unmatched: list[tuple[str, str]] = []

    def _check(self, method: str, path: str, req, resp) -> None:
        from charon_tpu.testutil import schemas

        route = schemas.find_route(method, path)
        if route is None:
            self.unmatched.append((method, path))
            return
        req_schema, resp_schema = route
        if req_schema is not None and req is not None:
            schemas.validate(req_schema, req, f"{method} {path} request")
        if resp_schema is not None:
            schemas.validate(resp_schema, resp, f"{method} {path} response")
        self.checked += 1

    async def _get(self, path: str, params=None) -> dict:
        j = await super()._get(path, params)
        self._check("GET", path, None, j)
        return j

    async def _post(self, path: str, payload, headers=None):
        j = await super()._post(path, payload, headers)
        self._check("POST", path, payload, j)
        return j
