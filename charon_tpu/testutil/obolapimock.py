"""In-process mock of the remote registry API (ref: testutil/obolapimock):
stores published locks and partial exit shares, aggregates exits at
threshold using tbls — the server side of app/obolapi.ObolApiClient.
"""

from __future__ import annotations

import json

from aiohttp import web

from charon_tpu import tbls


class ObolApiMock:
    def __init__(self, threshold: int) -> None:
        self.threshold = threshold
        self.locks: list[dict] = []
        # (lock_hash_hex, pubkey) -> {share_idx: sig}
        self.partials: dict[tuple[str, str], dict[int, bytes]] = {}
        self.exits: dict[tuple[str, str], dict] = {}
        self._runner: web.AppRunner | None = None
        self.port = 0

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        app = web.Application()
        app.router.add_post("/lock", self._post_lock)
        app.router.add_post(
            "/exp/partial_exits/{lock_hash}", self._post_partial
        )
        app.router.add_get(
            "/exp/exit/{lock_hash}/{pubkey}", self._get_exit
        )
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _post_lock(self, request: web.Request) -> web.Response:
        self.locks.append(await request.json())
        return web.json_response({"status": "published"}, status=201)

    async def _post_partial(self, request: web.Request) -> web.Response:
        body = await request.json()
        key = (request.match_info["lock_hash"], body["validator_pubkey"])
        shares = self.partials.setdefault(key, {})
        shares[int(body["share_idx"])] = bytes.fromhex(
            body["partial_signature"]
        )
        if len(shares) >= self.threshold and key not in self.exits:
            subset = dict(sorted(shares.items())[: self.threshold])
            sig = tbls.threshold_aggregate(subset)
            self.exits[key] = {
                "epoch": body["epoch"],
                "signature": "0x" + sig.hex(),
            }
        return web.json_response({"received": len(shares)})

    async def _get_exit(self, request: web.Request) -> web.Response:
        key = (
            request.match_info["lock_hash"],
            request.match_info["pubkey"],
        )
        if key not in self.exits:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(self.exits[key])
