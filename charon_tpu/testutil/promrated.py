"""promrated: standalone telemetry sidecar publishing validator
effectiveness stats as prometheus gauges.

Mirrors ref: testutil/promrated/ — a small service (not part of the
node) that periodically queries a rated-API-compatible endpoint for
network- and operator-level effectiveness (uptime, correctness,
inclusion delay, validator/proposer/attester effectiveness), sets
labelled gauges, and serves them on a /metrics endpoint. Queries retry
with the shared exponential backoff (ref: promrated/rated.go uses
app/expbackoff exactly like this).

The HTTP fetch is pluggable (`fetcher`) so tests drive it against a
local mock; the default fetcher speaks plain HTTP/1.1 over asyncio
streams (this image has no egress — production deployments would sit
next to their rated API mirror).
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from urllib.parse import urlsplit

from prometheus_client import CollectorRegistry, Gauge, generate_latest

_LABELS = ["cluster_network", "node_operator"]

# gauge name -> (rated JSON key, help) — ref: promrated/metrics.go
_GAUGES = {
    "promrated_network_uptime": ("avgUptime", "Uptime of the network."),
    "promrated_network_correctness": (
        "avgCorrectness",
        "Average correctness of the network.",
    ),
    "promrated_network_inclusion_delay": (
        "avgInclusionDelay",
        "Average inclusion delay of the network.",
    ),
    "promrated_network_effectiveness": (
        "avgValidatorEffectiveness",
        "Effectiveness of the network.",
    ),
    "promrated_network_proposer_effectiveness": (
        "avgProposerEffectiveness",
        "Proposer effectiveness of the network.",
    ),
    "promrated_network_attester_effectiveness": (
        "avgAttesterEffectiveness",
        "Attester effectiveness of the network.",
    ),
}


@dataclass
class Config:
    rated_endpoint: str
    rated_auth: str = ""  # bearer token; never logged (redact_url)
    networks: tuple[str, ...] = ("mainnet",)
    node_operators: tuple[str, ...] = ()
    monitoring_host: str = "127.0.0.1"
    monitoring_port: int = 0  # 0 = ephemeral
    interval: float = 24 * 3600.0  # rated stats are daily (promrated.go)


def redact_url(url: str) -> str:
    """Strip userinfo/query secrets for logging
    (ref: promrated.go redactURL)."""
    parts = urlsplit(url)
    host = parts.hostname or ""
    if parts.port:
        host += f":{parts.port}"
    return f"{parts.scheme}://{host}{parts.path}"


def parse_effectiveness(body: bytes) -> dict[str, float]:
    """rated effectiveness JSON -> metric values. Accepts both the
    network-overview shape (a list of per-validator-class rows, the
    'all' row wins) and the operator shape ({"data": [row]})
    (ref: promrated/rated.go parseNetworkMetrics/parseNodeOperatorMetrics)."""
    doc = json.loads(body)
    if isinstance(doc, dict) and "data" in doc:
        rows = doc["data"]
    elif isinstance(doc, list):
        rows = [
            r
            for r in doc
            if r.get("validatorType") in (None, "all", "allValidators")
        ]
    else:
        rows = [doc]
    if not rows:
        raise ValueError("rated response contains no effectiveness rows")
    row = rows[0]
    out = {}
    for name, (key, _help) in _GAUGES.items():
        if key in row:
            out[name] = float(row[key])
    if not out:
        raise ValueError("rated response carries no known effectiveness keys")
    return out


async def _default_fetcher(url: str, headers: dict[str, str]) -> bytes:
    """Minimal HTTP/1.1 GET over asyncio streams."""
    parts = urlsplit(url)
    https = parts.scheme == "https"
    port = parts.port or (443 if https else 80)
    # ssl for https endpoints — the Authorization bearer token must
    # never leave the host in cleartext
    reader, writer = await asyncio.open_connection(
        parts.hostname, port, ssl=True if https else None
    )
    try:
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        head = f"GET {path} HTTP/1.1\r\nHost: {parts.hostname}\r\n"
        for k, v in headers.items():
            head += f"{k}: {v}\r\n"
        head += "Connection: close\r\n\r\n"
        writer.write(head.encode())
        await writer.drain()
        status = await reader.readline()
        parts_s = status.split()
        if len(parts_s) < 2 or parts_s[1] != b"200":
            raise RuntimeError(f"rated API status: {status.decode().strip()}")
        while (await reader.readline()) not in (b"\r\n", b""):
            pass
        return await reader.read()
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:  # noqa: BLE001
            pass


class Promrated:
    """The service object: owns the registry, the /metrics endpoint and
    the periodic report loop (ref: promrated.go Run)."""

    def __init__(self, config: Config, fetcher=None) -> None:
        self.config = config
        self.fetcher = fetcher or _default_fetcher
        self.registry = CollectorRegistry()
        self.gauges = {
            name: Gauge(name, help_, _LABELS, registry=self.registry)
            for name, (_key, help_) in _GAUGES.items()
        }
        self.reports = 0
        self.report_errors = 0
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None

    async def _fetch(self, path: str, network: str) -> dict[str, float]:
        from charon_tpu.app import expbackoff as eb

        headers = {"X-Rated-Network": network}
        if self.config.rated_auth:
            headers["Authorization"] = f"Bearer {self.config.rated_auth}"
        url = self.config.rated_endpoint.rstrip("/") + path
        last: Exception | None = None
        for retries in range(5):
            try:
                return parse_effectiveness(await self.fetcher(url, headers))
            except Exception as e:  # noqa: BLE001 — retried with backoff
                last = e
                await asyncio.sleep(
                    eb.backoff_delay(eb.FAST_CONFIG, retries)
                )
        raise RuntimeError(f"rated API failed after retries: {last}")

    async def report_once(self) -> None:
        """One reporting pass over all networks/operators; individual
        failures count but do not abort the pass."""
        from charon_tpu.app import log

        for network in self.config.networks:
            targets = [("/v0/eth/network/overview", "network")] + [
                (f"/v0/eth/operators/{op}/effectiveness?size=1", op)
                for op in self.config.node_operators
            ]
            for path, operator in targets:
                try:
                    values = await self._fetch(path, network)
                except Exception as e:  # noqa: BLE001
                    self.report_errors += 1
                    log.warn(
                        "promrated query failed",
                        topic="promrated",
                        url=redact_url(
                            self.config.rated_endpoint.rstrip("/") + path
                        ),
                        err=str(e)[:160],
                    )
                    continue
                for name, value in values.items():
                    self.gauges[name].labels(network, operator).set(value)
        self.reports += 1

    async def start_monitoring(self) -> int:
        """Serve /metrics; returns the bound port."""

        async def handle(reader, writer):
            try:
                request = await reader.readline()
                while (await reader.readline()) not in (b"\r\n", b""):
                    pass
                path = (
                    request.split()[1].decode() if request.split() else "/"
                )
                if path.startswith("/metrics"):
                    body, status = generate_latest(self.registry), b"200 OK"
                else:
                    body, status = b"not found\n", b"404 Not Found"
                writer.write(
                    b"HTTP/1.1 %s\r\nContent-Length: %d\r\n"
                    b"Content-Type: text/plain; version=0.0.4\r\n\r\n"
                    % (status, len(body))
                )
                writer.write(body)
                await writer.drain()
            finally:
                writer.close()

        self._server = await asyncio.start_server(
            handle, self.config.monitoring_host, self.config.monitoring_port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def run(self, stop: asyncio.Event) -> None:
        """Report on startup then every interval until `stop` is set
        (ref: promrated.go Run's onStartup + daily ticker)."""
        await self.start_monitoring()
        while not stop.is_set():
            await self.report_once()
            try:
                await asyncio.wait_for(
                    stop.wait(), timeout=self.config.interval
                )
            except asyncio.TimeoutError:
                continue
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
