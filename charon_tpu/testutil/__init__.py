"""Test substrate: beaconmock, validatormock, simnet helpers.

Mirrors ref: testutil/ — the reference proves that building the fakes
*before* the real components makes the whole stack testable in one process
(ref: testutil/beaconmock/beaconmock.go, testutil/validatormock/,
app/app.go:862-897 simnet wiring).
"""
