"""BeaconMock: a programmable in-process fake beacon node.

Mirrors ref: testutil/beaconmock — deterministic duties, static chain
spec, canned attestation data, and recording submit endpoints, with
override options in the same spirit as beaconmock/options.go
(WithDeterministicAttesterDuties, WithSlotDuration, WithValidatorSet...).
All components consume it through the same duck-typed beacon interface as
the production client.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from charon_tpu.core.deadline import SlotClock
from charon_tpu.core.eth2data import (
    AttestationData,
    Checkpoint,
    Proposal,
)
from charon_tpu.core.types import PubKey
from charon_tpu.eth2util import spec


@dataclass
class BeaconMock:
    """validators: pubkey -> validator index. Deterministic single-committee
    attester duties per slot; proposer duty round-robin by slot."""

    validators: dict[PubKey, int] = field(default_factory=dict)
    genesis_time: float = field(default_factory=lambda: time.time())
    slot_duration: float = 1.0
    slots_per_epoch: int = 16
    synced: bool = True

    def __post_init__(self) -> None:
        self.attestations: list = []
        self.proposals: list = []
        self.registrations: list = []
        self.exits: list = []
        self.aggregates: list = []
        self.sync_messages: list = []
        self.contributions: list = []
        # test override hooks (ref: beaconmock/options.go pattern)
        self.attestation_data_fn = self._attestation_data_default
        # att-data roots served, so aggregate_attestation can look up the
        # exact data the root refers to
        self._att_data_by_root: dict[bytes, AttestationData] = {}
        # inclusion simulation: pool attestations land in the next block
        # materialized after submission; tests set drop_inclusions=True to
        # simulate a chain that never includes them
        # (ref: testutil/beaconmock + core/tracker/inclusion_internal_test.go)
        self.drop_inclusions = False
        self._att_pool: list = []
        self._blocks: dict[int, list] = {}

    # -- chain metadata ---------------------------------------------------

    def clock(self) -> SlotClock:
        return SlotClock(self.genesis_time, self.slot_duration)

    async def await_synced(self) -> None:
        return None

    # -- duties -----------------------------------------------------------

    async def attester_duties(self, epoch: int, validators: dict[PubKey, int]):
        """Every validator attests every slot in its own committee —
        deterministic (ref: beaconmock WithDeterministicAttesterDuties)."""
        out = []
        for slot in range(
            epoch * self.slots_per_epoch, (epoch + 1) * self.slots_per_epoch
        ):
            for i, (pubkey, vidx) in enumerate(sorted(validators.items())):
                out.append(
                    dict(
                        slot=slot,
                        pubkey=pubkey,
                        validator_index=vidx,
                        committee_index=i,
                        committee_length=1,
                        committees_at_slot=max(1, len(validators)),
                        validator_committee_index=0,
                    )
                )
        return out

    async def proposer_duties(self, epoch: int, validators: dict[PubKey, int]):
        out = []
        ordered = sorted(validators.items())
        if not ordered:
            return out
        for slot in range(
            epoch * self.slots_per_epoch, (epoch + 1) * self.slots_per_epoch
        ):
            pubkey, vidx = ordered[slot % len(ordered)]
            out.append(dict(slot=slot, pubkey=pubkey, validator_index=vidx))
        return out

    def sync_committee_position(self, vidx: int) -> int:
        """Deterministic position of a validator in the 512-member sync
        committee. Multiplying by an odd constant mod 512 is a bijection,
        so positions (and hence subcommittees AND positions WITHIN a
        subcommittee) spread non-trivially — a test that conflates
        position, subcommittee, or in-subcommittee index will fail."""
        return (vidx * 131 + 7) % 512

    async def sync_duties(self, epoch: int, validators: dict[PubKey, int]):
        """Every validator is a sync-committee member (deterministic)
        with a REAL committee position; the spec duty shape carries the
        positions (`validator_sync_committee_indices`), everything else
        (subcommittee = pos // 128, bit = pos % 128) is derived from
        them (ref: beaconmock WithDeterministicSyncCommDuties)."""
        return [
            dict(
                pubkey=pubkey,
                validator_index=vidx,
                sync_committee_indices=[self.sync_committee_position(vidx)],
            )
            for pubkey, vidx in sorted(validators.items())
        ]

    # -- duty data --------------------------------------------------------

    def _root(self, *parts) -> bytes:
        h = hashlib.sha256()
        for p in parts:
            h.update(str(p).encode())
        return h.digest()

    def _attestation_data_default(self, slot: int, committee_index: int) -> AttestationData:
        epoch = slot // self.slots_per_epoch
        return AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=self._root("block", slot),
            source=Checkpoint(max(0, epoch - 1), self._root("cp", epoch - 1)),
            target=Checkpoint(epoch, self._root("cp", epoch)),
        )

    async def attestation_data(self, slot: int, committee_index: int) -> AttestationData:
        data = self.attestation_data_fn(slot, committee_index)
        self._att_data_by_root[data.hash_tree_root()] = data
        return data

    async def block_proposal(self, slot: int, proposer_index: int, randao: bytes) -> Proposal:
        """A spec-complete deneb block: the full BeaconBlockBody container
        with a real (if minimal) execution payload, so the proposer flow
        exercises exactly the JSON/SSZ shapes a production beacon node
        serves (ref: testutil/beaconmock serves go-eth2-client spec
        blocks for the same reason)."""
        payload = spec.ExecutionPayloadDeneb(
            parent_hash=self._root("elblock", slot - 1),
            fee_recipient=b"\xfe" * 20,
            state_root=self._root("elstate", slot),
            receipts_root=self._root("elrcpt", slot),
            logs_bloom=bytes(256),
            prev_randao=hashlib.sha256(randao).digest(),
            block_number=slot,
            gas_limit=30_000_000,
            gas_used=21_000,
            timestamp=int(self.genesis_time) + slot,
            extra_data=b"beaconmock",
            base_fee_per_gas=7,
            block_hash=self._root("elblock", slot),
            transactions=(b"\x02" + self._root("tx", slot),),
            withdrawals=(),
        )
        block = spec.BeaconBlockDeneb(
            slot=slot,
            proposer_index=proposer_index,
            parent_root=self._root("block", slot - 1),
            state_root=self._root("state", slot, randao.hex()),
            body=spec.BeaconBlockBodyDeneb(
                randao_reveal=randao[:96].ljust(96, b"\x00"),
                eth1_data=spec.Eth1Data(
                    self._root("dep", slot), slot, self._root("eth1", slot)
                ),
                graffiti=b"beaconmock".ljust(32, b"\x00"),
                sync_aggregate=spec.SyncAggregate(
                    tuple([False] * 512), bytes(96)
                ),
                execution_payload=payload,
            ),
        )
        return Proposal(version="deneb", block=block)

    async def aggregate_attestation(self, slot: int, att_data_root: bytes):
        """Aggregate attestation for an att data root (the BN would merge
        pool attestations; deterministic here)."""
        from charon_tpu.core.eth2data import Attestation

        data = self._att_data_by_root.get(att_data_root)
        if data is None:
            data = self.attestation_data_fn(slot, 0)
        return Attestation(
            aggregation_bits=(True, True), data=data
        )

    async def sync_committee_block_root(self, slot: int) -> bytes:
        return self._root("block", slot)

    async def sync_contribution(self, slot: int, subcommittee_index: int, block_root: bytes):
        """The aggregation bits are the TRUE membership bits: position %
        128 for every registered validator whose committee position lands
        in this subcommittee (a real BN sets the bits of the messages it
        aggregated; the mock assumes every member's message arrived)."""
        from charon_tpu.core.eth2data import SyncCommitteeContribution

        bits = [False] * 128
        for vidx in self.validators.values():
            pos = self.sync_committee_position(vidx)
            if pos // 128 == subcommittee_index:
                bits[pos % 128] = True
        return SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=block_root,
            subcommittee_index=subcommittee_index,
            aggregation_bits=tuple(bits),
        )

    # -- chain/inclusion queries (ref: inclusion checker's BN surface) ----

    async def block_attestations(self, slot: int):
        """Attestations included in the block at `slot` (every slot has a
        block in the mock chain). Pool attestations submitted before this
        call land in the first block materialized afterwards — but only
        a block AFTER the attestation's slot, as on a real chain (an
        attestation can never appear in an earlier block)."""
        if slot not in self._blocks:
            take = [
                a
                for a in self._att_pool
                if getattr(a.data, "slot", slot - 1) < slot
            ]
            self._blocks[slot] = take
            self._att_pool = [a for a in self._att_pool if a not in take]
        return self._blocks[slot]

    async def block_root(self, slot: int):
        """Root of the block at `slot`: the submitted proposal's header
        root if one was broadcast for this slot, else the mock chain's
        deterministic root."""
        for proposal, _sig in self.proposals:
            if proposal.slot == slot:
                return proposal.hash_tree_root()
        return self._root("block", slot)

    # -- head events (ref: testutil/beaconmock/headproducer.go — the mock
    # serves SSE head events at /eth/v1/events; here subscribers get an
    # asyncio queue fed once per slot by run_head_producer) --------------

    def subscribe_head_events(self):
        import asyncio

        queue: asyncio.Queue = asyncio.Queue()
        if not hasattr(self, "_head_subs"):
            self._head_subs = []
        self._head_subs.append(queue)
        return queue

    async def run_head_producer(self, stop_event=None) -> None:
        """Emit one head event per slot until cancelled (or stop_event
        set). Event shape mirrors the eth2 SSE `head` topic."""
        import asyncio

        clock = self.clock()
        while stop_event is None or not stop_event.is_set():
            slot = clock.slot_at(time.time())
            await asyncio.sleep(
                max(0.0, clock.slot_start(slot + 1) - time.time())
            )
            event = {
                "slot": slot + 1,
                "block": "0x" + (await self.block_root(slot + 1)).hex(),
                "epoch_transition": (slot + 1) % self.slots_per_epoch == 0,
            }
            for q in getattr(self, "_head_subs", []):
                q.put_nowait(event)

    # -- fuzzing (ref: testutil/beaconmock/beaconmock_fuzz.go, enabled by
    # --simnet-beacon-mock-fuzz: responses become randomized but
    # shape-valid so the workflow's robustness is chaos-tested) ----------

    def enable_fuzz(self, seed: int = 0, error_rate: float = 0.1) -> None:
        import random as _random

        rng = _random.Random(seed)
        self._fuzz_rng = rng
        self._fuzz_error_rate = error_rate

        def fuzz_attestation_data(slot: int, committee_index: int):
            if rng.random() < error_rate:
                # ConnectionError: the honest simulation of a BN outage —
                # the workflow's retryer classifies it transient
                raise ConnectionError("beaconmock fuzz: synthetic BN error")
            epoch = slot // self.slots_per_epoch
            return AttestationData(
                slot=rng.randrange(max(1, slot * 2) + 1),
                index=rng.randrange(64),
                beacon_block_root=rng.randbytes(32),
                source=Checkpoint(max(0, epoch - 1), rng.randbytes(32)),
                target=Checkpoint(epoch, rng.randbytes(32)),
            )

        self.attestation_data_fn = fuzz_attestation_data

    # -- submissions ------------------------------------------------------

    async def submit_attestation(self, att) -> None:
        self.attestations.append(att)
        if not self.drop_inclusions:
            self._att_pool.append(att)

    async def submit_aggregate(self, agg_and_proof, signature: bytes) -> None:
        self.aggregates.append((agg_and_proof, signature))
        if not self.drop_inclusions:
            agg = getattr(agg_and_proof, "aggregate", None)
            if agg is not None:
                self._att_pool.append(agg)

    async def submit_sync_message(self, msg) -> None:
        self.sync_messages.append(msg)

    async def submit_contribution(self, contrib_and_proof, signature: bytes) -> None:
        self.contributions.append((contrib_and_proof, signature))

    async def submit_proposal(self, proposal, signature: bytes) -> None:
        self.proposals.append((proposal, signature))

    async def submit_registration(self, reg, signature: bytes) -> None:
        self.registrations.append((reg, signature))

    async def submit_exit(self, exit_msg, signature: bytes) -> None:
        self.exits.append((exit_msg, signature))
