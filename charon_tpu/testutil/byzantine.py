"""Byzantine adversary harness: seeded attack strategies against the
consensus and partial-signature planes, with differential device-vs-
oracle conformance checking and attributable evidence assertions.

The chaos plane (`testutil/chaos.py`) injects *accidental* faults —
drops, delays, crashes. This module injects *adversarial* behaviour:
equivocation, forged justifications, replay, floods, double-signing,
selective sends — the f-bounded Byzantine model the protocol claims to
tolerate (QBFT, arXiv:2002.03613: safety and liveness with up to
floor((n-1)/3) arbitrary faults). Three layers:

  * **Pure-QBFT harness** — `HarnessSigner` (seeded symmetric MACs
    standing in for the k1 message signatures, so `is_valid` /
    `verify_sender` semantics are real without the `cryptography`
    dependency), `ByzantineNet` (honest-node transports + adversarial
    injection + full broadcast capture), and `run_with_adversary`
    driving `core/qbft.run` engines for the honest set while an attack
    coroutine plays the adversary nodes. Everything derives from one
    `AdversaryParams.seed`.
  * **Differential conformance** — `DifferentialTbls` wraps the active
    tbls backend and re-checks every verify / recombine verdict
    lane-by-lane against the pure-python oracle (`PythonImpl`), so a
    device-plane bug that only manifests under adversarial inputs
    (forged G2 encodings, mixed valid/invalid lanes) is caught as a
    mismatch, not silently absorbed. Zero mismatches is a gate.
  * **Invariant helpers** — `assert_agreement` (safety: no two honest
    nodes decide different values), `assert_evidence_only` (every
    evidence entry names an adversary, never an honest peer), and
    `assert_no_mismatches`.

Determinism: adversary schedules draw from `Random(f"byz:{seed}:…")`
substreams; leader election uses `deterministic_leader` (sha256-based —
`hash()` is PYTHONHASHSEED-dependent and must not pick leaders in a
seeded battery).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import hmac
import random
from dataclasses import dataclass, replace

from charon_tpu import tbls
from charon_tpu.core import qbft
from charon_tpu.core.evidence import EvidenceRegistry
from charon_tpu.core.qbft import Definition, Msg, MsgType, Transport

# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AdversaryParams:
    """One seed drives the whole scenario: adversary identity, message
    schedules, and payload contents. `f` adversaries are the HIGHEST
    node indices (0-based) so round-1 leadership of a given instance
    stays searchable via `find_instance` without excluding seeds."""

    seed: int
    n: int = 4
    t: int = 3
    f: int = 1

    @property
    def adversaries(self) -> tuple[int, ...]:
        return tuple(range(self.n - self.f, self.n))

    @property
    def honest(self) -> tuple[int, ...]:
        return tuple(range(self.n - self.f))

    def stream(self, label: str) -> random.Random:
        """Deterministic substream per (seed, label), mirroring
        ChaosConfig.stream — injectors never perturb each other."""
        return random.Random(f"byz:{self.seed}:{label}")


def deterministic_leader(n: int):
    """Round-robin leader seeded by a *stable* hash of the instance.
    `hash()` would vary per process (PYTHONHASHSEED), silently changing
    which node leads and voiding seed-reproducibility."""

    def leader(instance, rnd: int) -> int:
        h = int.from_bytes(
            hashlib.sha256(repr(instance).encode()).digest()[:8], "big"
        )
        return (h + rnd) % n

    return leader


def find_instance(
    n: int, rnd: int, want_leader: int, prefix: str = "inst", limit: int = 512
) -> str:
    """Smallest `f"{prefix}-{i}"` whose round-`rnd` leader under
    `deterministic_leader(n)` is `want_leader` — lets a scenario cast a
    specific node (usually the adversary) as leader without touching
    the election rule itself."""
    leader = deterministic_leader(n)
    for i in range(limit):
        inst = f"{prefix}-{i}"
        if leader(inst, rnd) == want_leader:
            return inst
    raise AssertionError(
        f"no instance with leader {want_leader} at round {rnd} in {limit} tries"
    )


# ---------------------------------------------------------------------------
# Message authentication for the pure harness
# ---------------------------------------------------------------------------


class HarnessSigner:
    """Seeded per-node MAC keys standing in for the production k1
    message signatures (p2p path needs `cryptography`, absent here).
    The *semantics* match: `verify_sender` checks only the outer
    signature; `is_valid` additionally recurses into piggybacked
    justifications — exactly the split `_Engine._accept` relies on to
    attribute evidence safely. The harness knows every key, so an
    adversary can sign its OWN messages but can only `forge` garbage
    for another node's identity (tests never sign as honest nodes)."""

    def __init__(self, n: int, seed: int) -> None:
        self._keys = [
            hashlib.sha256(f"byz-key:{seed}:{i}".encode()).digest()
            for i in range(n)
        ]

    def _mac(self, source: int, digest: bytes) -> bytes:
        return hmac.new(self._keys[source], digest, hashlib.sha256).digest()

    def sign(self, msg: Msg) -> Msg:
        return replace(
            msg, signature=self._mac(msg.source, qbft.msg_digest(msg))
        )

    def verify_sender(self, msg: Msg) -> bool:
        if not (0 <= msg.source < len(self._keys)):
            return False
        return hmac.compare_digest(
            msg.signature, self._mac(msg.source, qbft.msg_digest(msg))
        )

    def is_valid(self, msg: Msg) -> bool:
        if not self.verify_sender(msg):
            return False
        return all(self.is_valid(j) for j in msg.justification)

    def forge(self, msg: Msg, rng: random.Random) -> Msg:
        """Claimed-source message with a garbage signature: fails both
        checks — the building block for framing attempts (which must
        produce NO evidence against the claimed source)."""
        return replace(msg, signature=rng.randbytes(32))


# ---------------------------------------------------------------------------
# Network fabric
# ---------------------------------------------------------------------------


class ByzantineNet:
    """Honest-node transports plus adversarial injection. Honest
    broadcasts deliver to every other honest transport and are captured
    in `log` (replay scenarios re-inject them verbatim). Adversary
    nodes run no engine: attacks inject crafted messages directly, with
    per-destination control (`inject`) for selective-send/split attacks
    or `inject_all` for symmetric ones."""

    def __init__(
        self,
        params: AdversaryParams,
        max_buffered_per_source: int = 128,
    ) -> None:
        self.params = params
        self.log: list[Msg] = []
        self.transports: dict[int, Transport] = {
            i: Transport(
                self._make_broadcast(i),
                max_buffered_per_source=max_buffered_per_source,
            )
            for i in params.honest
        }

    def _make_broadcast(self, src: int):
        async def broadcast(msg: Msg) -> None:
            self.log.append(msg)
            for dst, tr in self.transports.items():
                if dst != src:
                    tr.receive(msg)

        return broadcast

    def inject(self, dst: int, msg: Msg) -> bool:
        """Deliver one adversarial message to one honest node; False =
        refused at the transport bound."""
        return self.transports[dst].receive(msg)

    def inject_all(self, msg: Msg, exclude: tuple[int, ...] = ()) -> None:
        for dst, tr in self.transports.items():
            if dst not in exclude:
                tr.receive(msg)

    def drops(self) -> dict:
        """Merged typed transport-drop counters across honest nodes."""
        out: dict = {}
        for tr in self.transports.values():
            for key, cnt in tr.drops.items():
                out[key] = out.get(key, 0) + cnt
        return out


# ---------------------------------------------------------------------------
# Scenario runner
# ---------------------------------------------------------------------------


@dataclass
class ByzantineResult:
    decisions: dict[int, object]
    stats: dict[int, dict]
    evidence: EvidenceRegistry
    net: ByzantineNet
    signer: HarnessSigner

    def merged_drops(self) -> dict[str, int]:
        """Engine drop counters summed across honest nodes."""
        out: dict[str, int] = {}
        for s in self.stats.values():
            for k, v in s.get("drops", {}).items():
                out[k] = out.get(k, 0) + v
        return out


async def run_with_adversary(
    params: AdversaryParams,
    instance,
    attack=None,
    *,
    values: dict[int, object] | None = None,
    round_timeout: float = 0.15,
    max_stored_per_source: int = 128,
    max_buffered_per_source: int = 128,
    timeout_s: float = 20.0,
) -> ByzantineResult:
    """Run one QBFT instance with engines on the honest nodes only and
    `attack(net, signer, params)` playing the adversaries concurrently.

    All honest engines share ONE EvidenceRegistry (the battery asserts
    on the union — any single honest node mis-attributing would fail),
    and each gets its own stats dict so drop counters stay per-node.
    Raises asyncio.TimeoutError when liveness fails — the liveness
    assertion IS this await completing."""
    signer = HarnessSigner(params.n, params.seed)
    evidence = EvidenceRegistry()
    net = ByzantineNet(
        params, max_buffered_per_source=max_buffered_per_source
    )
    leader = deterministic_leader(params.n)
    stats: dict[int, dict] = {i: {} for i in params.honest}

    def make_defn() -> Definition:
        return Definition(
            nodes=params.n,
            leader=leader,
            timeout=lambda r: round_timeout * (1 + r / 4),
            is_valid=signer.is_valid,
            sign_msg=signer.sign,
            verify_sender=signer.verify_sender,
            max_stored_per_source=max_stored_per_source,
            on_evidence=evidence.record,
        )

    async def run_node(i: int):
        return await qbft.run(
            make_defn(),
            net.transports[i],
            instance,
            i,
            values[i] if values else f"value-{i}",
            stats=stats[i],
        )

    tasks = {
        i: asyncio.create_task(run_node(i)) for i in params.honest
    }
    attack_task = (
        asyncio.create_task(attack(net, signer, params))
        if attack is not None
        else None
    )
    try:
        done = await asyncio.wait_for(
            asyncio.gather(*tasks.values()), timeout_s
        )
    finally:
        for t in tasks.values():
            t.cancel()
        if attack_task is not None:
            attack_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await attack_task
    decisions = dict(zip(tasks.keys(), done))
    return ByzantineResult(decisions, stats, evidence, net, signer)


# ---------------------------------------------------------------------------
# Differential device-vs-oracle conformance
# ---------------------------------------------------------------------------

_RAISED = object()  # sentinel: the operation raised TblsError


class DifferentialTbls(tbls.Implementation):
    """Wraps the active tbls backend; every *verdict* operation is
    re-executed on the pure-python oracle and compared lane-by-lane.
    Mismatches are recorded (never raised mid-run — the scenario must
    finish so the report shows every divergent lane), and the inner
    backend's result/exception is passed through unchanged so the
    system under test behaves identically to an unwrapped run.

    Verdict caching: the oracle costs ~0.3 s per pairing on CPU, and
    adversarial floods repeat the same forged lanes — `(pk, data, sig)`
    keyed memoisation keeps scenario wall-time bounded without skipping
    any distinct lane. Key-generation/signing delegate uncompared
    (deterministic data-plane ops, covered by tbls conformance tests).
    """

    def __init__(self, inner=None, oracle=None) -> None:
        if inner is None:
            inner = tbls.get_implementation()
        if oracle is None:
            from charon_tpu.tbls.python_impl import PythonImpl

            oracle = PythonImpl()
        self.inner = inner
        self.oracle = oracle
        self.mismatches: list[dict] = []
        self.lanes_checked = 0
        self._verify_cache: dict[tuple, bool] = {}

    # -- uncompared delegation (key/signing data plane) -------------------

    def generate_secret_key(self):
        return self.inner.generate_secret_key()

    def secret_to_public_key(self, secret):
        return self.inner.secret_to_public_key(secret)

    def threshold_split(self, secret, total, threshold):
        return self.inner.threshold_split(secret, total, threshold)

    def recover_secret(self, shares, total, threshold):
        return self.inner.recover_secret(shares, total, threshold)

    def sign(self, secret, data):
        return self.inner.sign(secret, data)

    # -- compared verdicts ------------------------------------------------

    def _oracle_verify(self, pk, data, sig) -> bool:
        key = (pk, data, sig)
        got = self._verify_cache.get(key)
        if got is None:
            try:
                self.oracle.verify(pk, data, sig)
                got = True
            except tbls.TblsError:
                got = False
            self._verify_cache[key] = got
        return got

    def _mismatch(self, op: str, device, oracle, **ctx) -> None:
        self.mismatches.append(
            {"op": op, "device": device, "oracle": oracle, **ctx}
        )

    def verify(self, pubkey, data, sig) -> None:
        self.lanes_checked += 1
        err = None
        try:
            self.inner.verify(pubkey, data, sig)
            device_ok = True
        except tbls.TblsError as e:
            device_ok, err = False, e
        if device_ok != self._oracle_verify(pubkey, data, sig):
            self._mismatch("verify", device_ok, not device_ok)
        if err is not None:
            raise err

    def verify_batch(self, items) -> list:
        out = self.inner.verify_batch(items)
        for (pk, data, sig), device_ok in zip(items, out):
            self.lanes_checked += 1
            if bool(device_ok) != self._oracle_verify(pk, data, sig):
                self._mismatch(
                    "verify_batch", bool(device_ok), not device_ok
                )
        return out

    def verify_aggregate(self, pubkeys, data, sig) -> None:
        self.lanes_checked += 1
        err = None
        try:
            self.inner.verify_aggregate(pubkeys, data, sig)
            device_ok = True
        except tbls.TblsError as e:
            device_ok, err = False, e
        try:
            self.oracle.verify_aggregate(pubkeys, data, sig)
            oracle_ok = True
        except tbls.TblsError:
            oracle_ok = False
        if device_ok != oracle_ok:
            self._mismatch("verify_aggregate", device_ok, oracle_ok)
        if err is not None:
            raise err

    def _compare_recombine(self, op: str, partials, device) -> None:
        try:
            oracle = self.oracle.threshold_aggregate(partials)
        except tbls.TblsError:
            oracle = _RAISED
        if device != oracle:
            self._mismatch(
                op,
                device if device is _RAISED else device.hex(),
                oracle if oracle is _RAISED else oracle.hex(),
                indices=sorted(partials),
            )

    def threshold_aggregate(self, partials):
        self.lanes_checked += 1
        err, device = None, _RAISED
        try:
            device = self.inner.threshold_aggregate(partials)
        except tbls.TblsError as e:
            err = e
        self._compare_recombine("threshold_aggregate", partials, device)
        if err is not None:
            raise err
        return device

    def threshold_aggregate_batch(self, batch):
        out = self.inner.threshold_aggregate_batch(batch)
        for partials, device in zip(batch, out):
            self.lanes_checked += 1
            self._compare_recombine(
                "threshold_aggregate_batch", partials, device
            )
        return out

    def aggregate(self, sigs):
        self.lanes_checked += 1
        device = self.inner.aggregate(sigs)
        oracle = self.oracle.aggregate(sigs)
        if device != oracle:
            self._mismatch("aggregate", device.hex(), oracle.hex())
        return device

    def aggregate_batch(self, groups):
        return [self.aggregate(g) for g in groups]


@contextlib.contextmanager
def differential_backend():
    """Install DifferentialTbls over the active backend for the scope;
    yields it so the caller asserts `assert_no_mismatches(diff)` at the
    end. Always restores the previous backend (the conftest global-
    state fixture would also catch a leak, but scenarios should not
    rely on it)."""
    prev = tbls.get_implementation()
    diff = DifferentialTbls(inner=prev)
    tbls.set_implementation(diff)
    try:
        yield diff
    finally:
        tbls.set_implementation(prev)


# ---------------------------------------------------------------------------
# Invariant assertions
# ---------------------------------------------------------------------------


def assert_agreement(decisions: dict[int, object]) -> object:
    """Safety: every honest node decided, and decided the SAME value.
    Returns the agreed value."""
    assert decisions, "no honest decisions recorded"
    got = set(decisions.values())
    assert None not in got, f"undecided honest node: {decisions}"
    assert len(got) == 1, f"honest nodes disagree: {decisions}"
    return got.pop()


def assert_evidence_only(
    evidence: EvidenceRegistry, allowed
) -> None:
    """Attribution: every peer named in evidence is an allowed
    (adversary) identity — an honest peer appearing here is the PR 8
    acceptance failure mode (blaming the victim)."""
    named = evidence.peers()
    extra = named - set(allowed)
    assert not extra, (
        f"evidence names non-adversary peers {extra}: "
        f"{evidence.snapshot()}"
    )


def assert_no_mismatches(diff: DifferentialTbls) -> None:
    assert not diff.mismatches, (
        f"device-vs-oracle divergence on {len(diff.mismatches)} lanes "
        f"(of {diff.lanes_checked} checked): {diff.mismatches[:5]}"
    )
