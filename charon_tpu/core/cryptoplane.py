"""Slot-tick coalescer: ONE sharded device program per flush for the
whole node's concurrent crypto work — with a pipelined host plane.

The reference executes crypto per duty per signature on the CPU as calls
arrive (ref: core/sigagg/sigagg.go:84-122 per-pubkey ThresholdAggregate +
verify; core/parsigex/parsigex.go:94-98 and
core/validatorapi/validatorapi.go:1213 per-signature herumi verifies).
A TPU inverts the economics: launching a program costs milliseconds while
extra lanes in a launched batch cost microseconds — so the win is
batching ACROSS concurrent duties, not just within one (SURVEY §7 step 4;
VERDICT r3 next-step 3).

SlotCoalescer is that batching point. Components submit work from the
event loop and await results; submissions arriving within one coalescing
window are merged:

  * verify lanes (pk, root, sig) from ParSigEx inbound sets, the
    ValidatorAPI's pubshare checks, and SigAgg — concatenated into one
    sharded RLC verify (`SlotCryptoPlane.verify_host`);
  * threshold recombination jobs [V, t] from SigAgg — concatenated along
    the validator axis into one sharded recombine+verify step
    (`SlotCryptoPlane.recombine_host`).

Pipeline (ISSUE 3): a flush passes through three host/device stages so
host work overlaps device work and the event loop never runs bigint
math:

      submit ──► decode pool ──► window ──► pack (decode pool)
                 (sqrt/h2c off                  │
                  the loop)                     ▼
                                        device lane (1 thread)

  * DECODE — point decompression and hash-to-curve are pure-Python
    bigint work (milliseconds per lane); submissions ship their items to
    a sized ThreadPoolExecutor in chunks, so a slot-tick burst of N
    partial sigs costs the loop microseconds instead of N×ms.
  * PACK — once a window closes, array packing and RLC randomness also
    run on the decode pool, so window k may pack while the device still
    executes window k-1 (double buffering). On the device decode rung
    the parsed signature lanes pack straight from their raw wire bytes
    into device-ready limb arrays in one vectorized numpy pass
    (ops/limb.bytes_to_limbs_batch via ops/decompress.pack_parsed_* —
    ISSUE 7), retiring the O(lanes*limbs) per-int conversion that used
    to dominate this stage.
  * DEVICE — a single serialized worker thread launches the compiled
    program, preserving the device-contention and counter-integrity
    guarantees of the original single-lane design.

The coalescing window is adaptive: it grows toward `window_max` under
sustained multi-job load (catch more of the burst per program) and
decays back to the base once traffic thins; a submission carrying a duty
deadline (core/deadline.SlotClock.duty_deadline) pulls the flush earlier
so near-deadline work never waits out a grown window.

Decode failures (malformed compressed points) never reach the device:
those lanes fail on host and are replaced by lane-0 padding in the batch.

The plane object only needs `t`, `verify_host`, and `recombine_host` —
production passes `parallel.mesh.SlotCryptoPlane`; fast-tier tests pass
a counting fake backed by the pure-python oracle. Planes that also
expose the packed two-stage API (`pack_verify_inputs`/`verify_packed`,
`pack_inputs`/`recombine_packed`) get the pipelined pack stage; others
fall back to the single-stage host API on the device lane.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Sequence

from charon_tpu.crypto import g1g2
from charon_tpu.tbls import TblsError

try:
    # the parse half of ops/decompress is pure host code, but the ops
    # PACKAGE init configures jax (x64) on import — on a jax-less host
    # the device decode rung is simply unavailable and the coalescer
    # stays on the python rung (the PR 2 ladder's floor).
    from charon_tpu.ops import decompress as _dec
except ImportError:  # pragma: no cover — jax not installed
    _dec = None


class _ParsedPointNA:
    """Sentinel parsed-lane type for jax-less hosts: nothing is ever an
    instance, so every isinstance() site degrades to the point path."""


_PARSED_T = _dec.ParsedPoint if _dec is not None else _ParsedPointNA


@dataclass
class _VerifyJob:
    lanes: list  # [(pk_pt, msg_pt, sig_pt) | None] — None = host decode fail
    fut: asyncio.Future
    decode_delays: tuple = ()  # decode-pool queue delay per chunk
    decode_spans: tuple = ()  # wall-clock (start, end) per decode chunk
    parent: tuple | None = None  # submitter's (trace_id, span_id)
    tenant: str | None = None  # submitting tenant (core/cryptosvc)


@dataclass
class _RecombineJob:
    # all rows [V][t] / [V]; lanes with decode failures are pre-failed
    pubshares: list
    msgs: list
    partials: list
    group_pks: list
    indices: list
    prefail: list  # [V] bool — True: fail without consulting the device
    fut: asyncio.Future
    decode_delays: tuple = ()
    decode_spans: tuple = ()
    parent: tuple | None = None
    tenant: str | None = None


@dataclass(frozen=True)
class FlushStats:
    """Per-flush pipeline observability, delivered to `stats_hook` from
    the device worker thread (thread-safe sinks only).

    The stage spans (wall-clock `time.time()` windows) plus the
    submitters' trace contexts in `parents` are everything
    app/tracer.plane_span_bridge needs to bridge the flush into real
    duty-rooted tracer spans; bench_hostplane.py computes its
    host/device overlap from the same fields."""

    jobs: int
    lanes: int
    flush_seconds: float  # device-lane wall clock (pack excluded)
    window: float  # adaptive window in force when the flush armed
    inflight: int  # device-lane depth at submit (1 when single-buffered
    # idle traffic; >= 2 means this flush double-buffered behind an
    # in-flight program)
    pad_lanes: int | None  # bucket-padding lanes shipped (packed path)
    padded_lanes: int | None  # total lanes after bucket padding
    decode_queue_seconds: tuple[float, ...]  # decode-pool queue delays
    fallback: bool = False  # served by the python-spec rung
    # decode-source breakdown of this flush (ISSUE 5): point lookups
    # served by the tpu_impl LRU caches (pubkeys/messages/pubshares) vs
    # signature lanes decompressed on device (parsed lanes shipped to a
    # decode-fused program) vs on host (python bigint decode)
    decode_mode: str = "python"  # decode rung that served the flush
    decode_cache_lanes: int = 0
    decode_device_lanes: int = 0
    decode_python_lanes: int = 0
    # wall-clock stage windows of THIS flush's pipeline pass
    decode_spans: tuple[tuple[float, float], ...] = ()  # per decode chunk
    pack_span: tuple[float, float] | None = None
    device_span: tuple[float, float] | None = None
    # (trace_id, span_id) captured from each submission's active span
    parents: tuple[tuple[str, str], ...] = ()
    # live lanes per submitting tenant (ISSUE 8): (tenant_id, lanes)
    # pairs for the jobs that carried a tenant tag — the per-flush
    # attribution the tenant-labeled metric families and the span
    # bridge's tenant attrs are built from
    tenant_lanes: tuple[tuple[str, int], ...] = ()


class PlaneConfigError(ValueError):
    """Invalid crypto-plane configuration (typed-errors invariant: a
    config mistake at the plane boundary must be distinguishable from
    wire/crypto failures — it is a deploy bug, never degradable load)."""


def kernel_inventory() -> dict:
    """Machine-readable inventory of every registered device kernel
    family behind this plane (ISSUE 11): the blsops engine kernels plus
    the mesh program variants, registered on canonical bucket-ladder
    shapes. Consumers: the jaxpr static analyzer
    (charon_tpu/analysis/jaxpr_check.py traces each family and gates
    its primitive census against tests/testdata/kernel_manifest.json)
    and the per-platform startup auto-tuner (core/autotune.resolve
    walks this registry before micro-benching its candidate axes and
    records the family names in the persisted profile — ROADMAP item
    3). Raises PlaneConfigError on a
    jax-less host (asking for the device inventory without jax is a
    deploy/config mistake) — inventory is an analysis/tuning surface,
    not a duty-path one."""
    if _dec is None:
        raise PlaneConfigError(
            "kernel inventory requires jax (ops import failed)"
        )
    from charon_tpu.ops import blsops
    from charon_tpu.parallel import mesh as _mesh

    _mesh.register_analysis_families()
    return {
        name: {"sentinel": fam.sentinel}
        for name, fam in sorted(blsops.kernel_families().items())
    }


def _decode_pubkey(pk: bytes):
    from charon_tpu.tbls.tpu_impl import _cached_pubkey_point

    return _cached_pubkey_point(pk)


def _decode_sig(sig: bytes):
    from charon_tpu.tbls.python_impl import sig_to_point

    pt = sig_to_point(sig, subgroup_check=False)
    if pt is None:
        raise TblsError("infinite signature")
    return pt


def _msg_point(root: bytes):
    from charon_tpu.tbls.tpu_impl import _cached_msg_point

    return _cached_msg_point(root)


def _decode_verify_lane(item):
    """(pk, root, sig) bytes -> decoded point triple, or None on any
    malformed encoding (the lane fails on host, never ships)."""
    pk, root, sig = item
    try:
        return (_decode_pubkey(pk), _msg_point(root), _decode_sig(sig))
    except (TblsError, ValueError):
        return None


def _parse_verify_lane(item):
    """decode_mode=device twin of _decode_verify_lane: the pubkey and
    message still come from the host LRU caches (cache-hit dominated),
    but the signature is only PARSED (flags + range checks, no field
    arithmetic) — the Fp2 sqrt, sign selection, on-curve and subgroup
    checks run batched on device inside the flush program. Lanes the
    parse already rejects (malformed flags, x >= p, infinity) fail on
    host and never ship."""
    pk, root, sig = item
    try:
        pk_pt, msg_pt = _decode_pubkey(pk), _msg_point(root)
    except (TblsError, ValueError):
        return None
    parsed = _dec.parse_g2_lane(sig)
    if not parsed.ok or parsed.infinity:
        return None
    return (pk_pt, msg_pt, parsed)


def _lane_to_points(lane):
    """Parsed verify lane -> point triple on the python rung (device
    decode unavailable / degraded). Point lanes pass through; a parsed
    signature that fails host decompression turns the lane into None."""
    if lane is None or not isinstance(lane[2], _PARSED_T):
        return lane
    try:
        return (lane[0], lane[1], _decode_sig(lane[2].raw))
    except (TblsError, ValueError):
        return None


class SlotCoalescer:
    """Merges concurrent verify / recombine submissions into single
    sharded device programs (see module docstring).

    window: base seconds to wait after the first submission before
    flushing; the adaptive controller moves the live window within
    [window, window_max] under load and deadlines cap it down to
    window_min.
    decode_workers: decode/pack pool size; 0 disables the pipeline
    entirely (decode runs synchronously on the caller — the pre-pipeline
    path, kept for A/B benching). The pool is created lazily on first
    use, so an idle or disabled plane owns no threads.
    flushes / coalesced_flushes / lanes_flushed: observability counters
    (exported as node metrics by app/run.py).
    """

    # decode-pool chunking: large enough to amortize executor submission,
    # small enough to spread one burst across the workers
    DECODE_CHUNK = 16
    # adaptive window controller: grow when a flush coalesced >=2 jobs or
    # carried a burst, decay back to the base window otherwise
    WINDOW_GROW = 1.5
    WINDOW_DECAY = 0.75
    GROW_LANES = 64
    # graded deadline shrink: spend at most this fraction of the time
    # remaining before the duty deadline on coalescing — with a 60 s
    # expiry window the cap is inert (plenty of time), but a retrying
    # near-expiry submission (seconds left) flushes in milliseconds
    # instead of waiting out a load-grown window
    DEADLINE_WINDOW_FRAC = 0.01

    def __init__(
        self,
        plane,
        window: float = 0.02,
        metrics_hook=None,
        plane_factory=None,
        window_min: float = 0.002,
        window_max: float = 0.08,
        decode_workers: int = 4,
        stats_hook=None,
        decode_mode: str = "auto",
    ):
        import concurrent.futures

        self.plane = plane
        self.window = window
        self.window_min = min(window_min, window)
        self.window_max = max(window_max, window)
        self.decode_workers = decode_workers
        # signature-decode routing (ISSUE 5): "device" parses compressed
        # signatures on host (cheap flag/range checks) and runs the
        # field work (sqrt, sign, on-curve, psi subgroup) batched inside
        # the flush program via the plane's *_parsed API; "python" keeps
        # the host bigint decode; "auto" resolves to device only on a
        # TPU backend with a parsed-capable plane. python is ALSO the
        # degradation rung below device (PR 2 ladder): a device failure
        # in a parsed flush steps this coalescer down permanently.
        if decode_mode not in ("auto", "device", "python"):
            raise PlaneConfigError(f"bad decode_mode {decode_mode!r}")
        self.decode_mode = decode_mode
        self._decode_live: str | None = None  # resolved lazily
        # msm-off degradation rung (mirrors tbls/tpu_impl._rlc_guarded):
        # a device/compile failure in the newest kernel family is not a
        # crypto verdict. plane_factory() rebuilds the plane after the
        # flag flip so its jitted programs re-trace; without a factory
        # there is no degrade at all (the flag stays untouched — a retry
        # without a rebuild would re-run the identical failed executable).
        self._plane_factory = plane_factory
        self._degraded = False
        self._closed = False
        self._verify_q: list[_VerifyJob] = []
        self._recombine_q: list[_RecombineJob] = []
        self._flush_task: asyncio.Task | None = None
        self._flush_at: float = 0.0  # monotonic flush target of armed task
        self._flush_wake = asyncio.Event()
        self._queue_deadline: float | None = None  # monotonic, min over jobs
        self._wall_offset = 0.0  # wall->monotonic, snapshotted per window
        # submissions mid-decode (closing windows wait for these)
        self._decode_tickets: set[asyncio.Future] = set()
        self._window_current = window
        # first-dispatch gate (app/run.py wires the autotune tune_done
        # event here): the boot-time tuner's trial.apply() flips the
        # global dispatch flags and drops the jitted-kernel caches, so
        # a flush racing the tuning window compiles under a transient
        # trial config and immediately loses its executable. Flushes
        # queue behind the gate (and keep coalescing) until it fires;
        # None (tests, CLI tools, no tuner) means no gating at all.
        self.dispatch_gate: asyncio.Event | None = None
        self.gated_flushes = 0  # flushes that waited on dispatch_gate
        # single-threaded device lane: a second window can elapse while a
        # device program is still running; its flush must QUEUE behind
        # the first, not race it (device contention + counter integrity)
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="crypto-plane"
        )
        # decode/pack pool — created lazily so a coalescer that never
        # sees traffic (or runs with decode_workers=0) owns no threads
        self._decode_pool: concurrent.futures.ThreadPoolExecutor | None = None
        self.flushes = 0
        self.coalesced_flushes = 0  # flushes that merged >= 2 jobs
        self.lanes_flushed = 0
        self.host_fallback_flushes = 0  # served by the python-spec rung
        self.pack_fallbacks = 0  # pack-stage failures (single-stage flush)
        self.pad_lanes_flushed = 0  # bucket-padding lanes shipped
        self.overlapped_flushes = 0  # submitted while the device was busy
        self._inflight = 0  # flushes inside the device lane (incl. queued)
        self.max_inflight = 0
        # called after each flush with (jobs, lanes) — thread-safe
        # counters only (runs on the device worker thread)
        self.metrics_hook = metrics_hook
        # richer per-flush pipeline stats (FlushStats) — same threading
        # contract as metrics_hook. Stage timing travels IN the stats
        # (decode_spans/pack_span/device_span wall-clock windows), so
        # the tracer bridge and bench_hostplane.py both read per-flush
        # spans from here instead of a coalescer-global trace list.
        self.stats_hook = stats_hook
        # bulk warm-up observability (ISSUE 6): called with the stats
        # dict of every warm_caches() pass (worker thread — thread-safe
        # sinks only); counters for the /metrics families
        self.warmup_hook = None
        self.warmups = 0
        self.warmup_lanes = 0

    @property
    def t(self) -> int:
        return self.plane.t

    # -- decode-mode resolution (ISSUE 5) ----------------------------------

    def _plane_has_parsed_api(self) -> bool:
        return self._plane_has_packed_api() and all(
            hasattr(self.plane, name)
            for name in (
                "pack_verify_inputs_parsed",
                "verify_packed_parsed",
                "pack_inputs_parsed",
                "recombine_packed_parsed",
            )
        )

    def _decode_rung(self) -> str:
        """The decode rung in force: 'device' ships parsed signature
        lanes to decode-fused programs, 'python' decompresses on host.
        Resolved once, lazily: 'auto' means device only on a TPU backend
        (CPU sqrt chains are slower than the host bigints they replace)
        AND a parsed-capable plane; a forced 'device' still needs the
        plane API (test fakes without it stay on python). A device
        failure in a parsed flush steps the live rung down to python
        permanently (PR 2 ladder)."""
        if self._decode_live is None:
            mode = self.decode_mode
            if _dec is None or not self._plane_has_parsed_api():
                mode = "python"
            elif mode == "auto":
                # the parsed API implies a real jax plane, so this
                # import resolves to the already-loaded module
                from charon_tpu.ops import limb

                mode = "device" if limb._is_tpu_backend() else "python"
            self._decode_live = mode
        return self._decode_live

    @property
    def current_window(self) -> float:
        """The adaptive coalescing window currently in force."""
        return self._window_current

    def close(self) -> None:
        """Shut down the worker pools (idempotent). Late flushes fail
        their waiters fast instead of tripping the degradation rung."""
        self._closed = True
        self._executor.shutdown(wait=False)
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=False)
            self._decode_pool = None

    # -- decode pool (host stage 1) ---------------------------------------

    def _pool(self):
        if self._decode_pool is None:
            import concurrent.futures

            self._decode_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="crypto-decode",
            )
        return self._decode_pool

    async def _map_offloop(self, fn, items: list):
        """Apply `fn` per item with the bigint work OFF the event loop:
        items ship to the decode pool in DECODE_CHUNK chunks (batched
        submission — one executor hop per chunk, not per lane). Returns
        (results, per-chunk queue delays, per-chunk wall-clock spans) —
        both travel with the job so each flush's stats report ITS OWN
        decode queueing/timing, not whatever the concurrent next window
        happens to be decoding. With the pool disabled the map runs
        inline on the caller — the pre-pipeline synchronous path
        bench_hostplane.py baselines."""
        # closed: inline decode instead of resurrecting a pool nobody
        # will shut down (the flush fails these waiters fast anyway)
        if self.decode_workers <= 0 or self._closed:
            # stage spans are ATTRIBUTION: wall-clock windows bridged
            # into duty traces (tracer.plane_span_bridge), never math
            w0 = time.time()  # lint: allow(monotonic-clock)
            out = [fn(it) for it in items]
            return out, (), ((w0, time.time()),)  # lint: allow(monotonic-clock)
        loop = asyncio.get_running_loop()
        pool = self._pool()
        submitted = time.monotonic()

        def run_chunk(chunk):
            t0 = time.monotonic()
            # wall span = trace attribution; the queue DELAY above it
            # stays on the monotonic base
            w0 = time.time()  # lint: allow(monotonic-clock)
            out = [fn(it) for it in chunk]
            return out, t0 - submitted, (w0, time.time())  # lint: allow(monotonic-clock)

        chunks = [
            items[i : i + self.DECODE_CHUNK]
            for i in range(0, len(items), self.DECODE_CHUNK)
        ]
        parts = await asyncio.gather(
            *(loop.run_in_executor(pool, run_chunk, c) for c in chunks)
        )
        return (
            [lane for part, _, _ in parts for lane in part],
            tuple(delay for _, delay, _ in parts),
            tuple(span for _, _, span in parts),
        )

    # -- submission APIs (event-loop side) --------------------------------

    @staticmethod
    def _submit_ctx():
        """(trace_id, span_id) of the submitting context's active span —
        how a flush's stage spans find their way into the duty traces
        whose work they merged (app/tracer.plane_span_bridge)."""
        from charon_tpu.app.tracer import current_ctx  # lazy: core !-> app

        return current_ctx()

    async def verify(
        self,
        items: Sequence[tuple[bytes, bytes, bytes]],
        deadline: float | None = None,
        tenant: str | None = None,
    ) -> list[bool]:
        """Batch-verify (pubkey_bytes, signing_root, sig_bytes) lanes.
        Returns per-lane validity; malformed encodings are False.
        deadline: optional absolute wall-clock (time.time) duty deadline
        — pulls the flush earlier when the window would overshoot it.
        tenant: optional tenant id (core/cryptosvc) for per-flush
        attribution in FlushStats/metrics/span attrs."""
        if not items:
            return []
        loop = asyncio.get_running_loop()
        # decode ticket: an armed flush whose window closes while this
        # submission is still decoding WAITS for it — otherwise a burst
        # whose cold-cache decode outlasts the window would split into
        # one device program per submission (the anti-coalescing bug)
        ticket = loop.create_future()
        self._decode_tickets.add(ticket)
        try:
            decode_fn = (
                _parse_verify_lane
                if self._decode_rung() == "device"
                else _decode_verify_lane
            )
            lanes, delays, spans = await self._map_offloop(
                decode_fn, list(items)
            )
            job = _VerifyJob(
                lanes=lanes,
                fut=loop.create_future(),
                decode_delays=delays,
                decode_spans=spans,
                parent=self._submit_ctx(),
                tenant=tenant,
            )
            self._verify_q.append(job)
            self._arm(deadline)
        finally:
            # resolve AFTER the append above (same synchronous block):
            # the waiting flush wakes only on the next scheduler turn,
            # so the job is guaranteed to be in the collected queue
            self._decode_tickets.discard(ticket)
            if not ticket.done():
                ticket.set_result(None)
        return await job.fut

    async def recombine(
        self,
        pubshares: Sequence[Sequence[bytes]],
        roots: Sequence[bytes],
        partials: Sequence[Sequence[bytes]],
        group_pks: Sequence[bytes],
        indices: Sequence[Sequence[int]],
        deadline: float | None = None,
        tenant: str | None = None,
    ) -> tuple[list[bytes | None], list[bool]]:
        """Threshold-recombine + verify a duty's [V, t] workload.
        Returns ([V] group signature bytes or None, [V] ok flags)."""
        if not roots:
            return [], []
        t = self.plane.t
        device_decode = self._decode_rung() == "device"

        def parse_partial(sig: bytes):
            parsed = _dec.parse_g2_lane(sig)
            if not parsed.ok or parsed.infinity:
                raise TblsError("malformed partial signature")
            return parsed

        def decode_row(row):
            ps_row, root, sig_row, gpk, idx_row = row
            try:
                if len(sig_row) != t or len(ps_row) != t or len(idx_row) != t:
                    raise TblsError(f"need exactly t={t} partials per lane")
                if any(i <= 0 for i in idx_row):
                    raise TblsError("share indices are 1-based")
                return (
                    [_decode_pubkey(p) for p in ps_row],
                    _msg_point(root),
                    # device rung: partials ship as PARSED lanes (no
                    # field arithmetic here) — the flush program
                    # decompresses them; host-parse rejects prefail
                    [
                        parse_partial(s) if device_decode else _decode_sig(s)
                        for s in sig_row
                    ],
                    _decode_pubkey(gpk),
                    list(idx_row),
                    False,
                )
            except (TblsError, ValueError):
                # prefail row — skipped during batch assembly (never
                # shipped to the device); the lane is failed on host
                return (None, None, None, None, None, True)

        loop = asyncio.get_running_loop()
        ticket = loop.create_future()  # see verify() for the contract
        self._decode_tickets.add(ticket)
        try:
            rows, delays, spans = await self._map_offloop(
                decode_row,
                list(zip(pubshares, roots, partials, group_pks, indices)),
            )
            ps_rows, msg_pts, sig_rows, gpk_pts, idx_rows, prefail = (
                [list(col) for col in zip(*rows)]
            )
            job = _RecombineJob(
                pubshares=ps_rows,
                msgs=msg_pts,
                partials=sig_rows,
                group_pks=gpk_pts,
                indices=idx_rows,
                prefail=prefail,
                fut=loop.create_future(),
                decode_delays=delays,
                decode_spans=spans,
                parent=self._submit_ctx(),
                tenant=tenant,
            )
            self._recombine_q.append(job)
            self._arm(deadline)
        finally:
            self._decode_tickets.discard(ticket)
            if not ticket.done():
                ticket.set_result(None)
        sigs_pts, oks = await job.fut
        return (
            [
                g1g2.g2_to_bytes(pt) if pt is not None else None
                for pt in sigs_pts
            ],
            oks,
        )

    # -- flush machinery ---------------------------------------------------

    def _arm(self, deadline: float | None = None) -> None:
        now = time.monotonic()
        new_window = self._flush_task is None or self._flush_task.done()
        if new_window:
            # duty deadlines are wall-clock (core/deadline.SlotClock)
            # but the flush timer runs on the monotonic base — snapshot
            # the wall->monotonic offset ONCE per window. Converting per
            # call meant a host clock step mid-window (chaos clock-skew)
            # translated later submissions' deadlines inconsistently,
            # wrongly collapsing or stretching the armed window.
            self._wall_offset = now - time.time()  # lint: allow(monotonic-clock) — THE one-shot wall->mono anchor (PR 8 fix)
        if deadline is not None:
            dl_mono = max(now, deadline + self._wall_offset)
            if self._queue_deadline is None or dl_mono < self._queue_deadline:
                self._queue_deadline = dl_mono
        target = now + self._window_current
        if self._queue_deadline is not None:
            # graded shrink toward the deadline, never below window_min
            # (give concurrent submissions a beat to coalesce regardless)
            remaining = self._queue_deadline - now
            cap = max(
                self.window_min, remaining * self.DEADLINE_WINDOW_FRAC
            )
            target = min(target, now + cap)
        if new_window:
            self._flush_at = target
            # fresh Event per armed task: asyncio primitives bind to the
            # running loop on first use, and one coalescer may serve
            # several asyncio.run() lifetimes (tests, CLI tools)
            self._flush_wake = asyncio.Event()
            self._flush_task = asyncio.create_task(self._flush_after_window())
        elif target < self._flush_at:
            # a tighter deadline arrived while the window timer sleeps:
            # pull the armed flush earlier (never later)
            self._flush_at = target
            self._flush_wake.set()

    async def _flush_after_window(self) -> None:
        while True:
            self._flush_wake.clear()
            remaining = self._flush_at - time.monotonic()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(
                    self._flush_wake.wait(), timeout=remaining
                )
            except asyncio.TimeoutError:
                pass
        gate = self.dispatch_gate
        if gate is not None and not gate.is_set():
            # startup tuner still settling the kernel dispatch flags:
            # queue this flush behind it. Waiting BEFORE the snapshot
            # also lets submissions arriving during the tuning window
            # coalesce into this flush instead of arming more of them.
            self.gated_flushes += 1
            await gate.wait()
        # submissions still mid-decode when the window closed join this
        # flush (ONE snapshot — later arrivals take the next window, so
        # sustained load cannot defer the flush unboundedly)
        pending = list(self._decode_tickets)
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        vq, self._verify_q = self._verify_q, []
        rq, self._recombine_q = self._recombine_q, []
        # new submissions from here on arm a fresh flush task — its
        # decode/pack stages overlap this flush's device stage
        self._flush_task = None
        self._queue_deadline = None
        if not vq and not rq:
            return
        if self._closed:
            # shutdown raced a late submission: fail the waiters fast —
            # a closed-executor RuntimeError must not masquerade as a
            # device failure and burn the msm-off rung
            for job in [*vq, *rq]:
                if not job.fut.done():
                    job.fut.set_exception(TblsError("crypto plane closed"))
            return
        window_used = self._window_current
        self._adapt_window(vq, rq)
        loop = asyncio.get_running_loop()
        # host stage 2: pack the batch on the decode pool so the device
        # lane (possibly still executing the previous window) is never
        # blocked on numpy conversion of Python ints
        packed = None
        if self.decode_workers > 0 and self._plane_has_packed_api():
            try:
                packed = await loop.run_in_executor(
                    self._pool(), self._pack_flush, vq, rq
                )
            except Exception as e:  # noqa: BLE001 — pack bug: the
                # single-stage path repacks on the device lane, which
                # still serves the flush but silently un-pipelines it —
                # count + warn so a persistent pack failure is visible
                packed = None
                self.pack_fallbacks += 1
                if self.pack_fallbacks == 1 or self.pack_fallbacks % 100 == 0:
                    from charon_tpu.app import log

                    log.warn(
                        "crypto plane pack stage failed; flushing "
                        "single-stage on the device lane",
                        topic="cryptoplane",
                        count=self.pack_fallbacks,
                        err=f"{type(e).__name__}: {str(e)[:160]}",
                    )
        inflight = self._inflight + 1
        self._inflight = inflight
        self.max_inflight = max(self.max_inflight, inflight)
        if inflight >= 2:
            self.overlapped_flushes += 1
        try:
            try:
                vres, rres = await loop.run_in_executor(
                    self._executor,
                    self._run_device,
                    vq,
                    rq,
                    packed,
                    window_used,
                    inflight,
                )
            except Exception as e:  # noqa: BLE001 — degrade or fail waiters
                # first rung below the device decode: step decode down
                # to python for good and retry the SAME batch — the
                # decode-fused programs are the newest kernel family, so
                # a failure there must not cost the older point-input
                # path (or burn the process-wide msm-off rung)
                retried = await self._decode_stepdown_and_retry(
                    vq, rq, e, window_used, inflight
                )
                if retried is None:
                    retried = await self._degrade_and_retry(
                        vq, rq, e, window_used, inflight
                    )
                if retried is None:
                    # last rung: the pure-python spec oracle. Orders of
                    # magnitude slower than the device, but a wedged
                    # accelerator must cost latency, not the duty — the
                    # signing plane stays live on the degraded backend
                    # (ISSUE: degrade TPU -> native -> python-spec).
                    try:
                        retried = await loop.run_in_executor(
                            self._executor, self._run_host_oracle, vq, rq
                        )
                        self.host_fallback_flushes += 1
                        from charon_tpu.app import log

                        log.warn(
                            "crypto plane flush served by python-spec "
                            "host fallback",
                            topic="cryptoplane",
                            rung="host-oracle",
                            err=f"{type(e).__name__}: {str(e)[:160]}",
                        )
                    except Exception:  # noqa: BLE001 — rungs exhausted
                        for job in [*vq, *rq]:
                            if not job.fut.done():
                                job.fut.set_exception(
                                    TblsError(
                                        f"crypto plane flush failed: {e}"
                                    )
                                )
                        return
                vres, rres = retried
        finally:
            self._inflight -= 1
        for job, res in zip(vq, vres):
            if not job.fut.done():
                job.fut.set_result(res)
        for job, res in zip(rq, rres):
            if not job.fut.done():
                job.fut.set_result(res)

    def _adapt_window(self, vq, rq) -> None:
        """Sustained load (multi-job windows or lane bursts) grows the
        window toward window_max — each program catches more of the
        burst; light traffic decays it back to the base so single duties
        never wait out a grown window."""
        jobs = len(vq) + len(rq)
        lanes = sum(len(j.lanes) for j in vq) + sum(len(j.msgs) for j in rq)
        if jobs >= 2 or lanes >= self.GROW_LANES:
            self._window_current = min(
                self.window_max, self._window_current * self.WINDOW_GROW
            )
        else:
            self._window_current = max(
                self.window, self._window_current * self.WINDOW_DECAY
            )

    def _plane_has_packed_api(self) -> bool:
        return all(
            hasattr(self.plane, name)
            for name in (
                "pack_verify_inputs",
                "make_lane_rand",
                "verify_packed",
                "pack_inputs",
                "make_rand",
                "recombine_packed",
            )
        )

    @staticmethod
    def _flat_verify_lanes(vq: list[_VerifyJob]) -> list:
        return [lane for job in vq for lane in job.lanes if lane is not None]

    def _normalize_jobs(self, vq, rq) -> bool:
        """One flush, one lane representation (worker thread). Returns
        True when the flush ships PARSED signature lanes to the
        decode-fused device programs. That needs the device rung still
        live AND every lane parsed — a rung step-down between
        submissions can leave a window holding both kinds, and the
        retry of a failed parsed flush arrives here after the step-down;
        in either case the parsed lanes convert to points on host (the
        python rung), flipping a job's prefail slot when a partial
        fails host decompression. Idempotent, cheap when nothing is
        parsed."""
        kinds = set()
        for job in vq:
            for lane in job.lanes:
                if lane is not None:
                    kinds.add(isinstance(lane[2], _PARSED_T))
        for job in rq:
            for i, pf in enumerate(job.prefail):
                if not pf:
                    kinds.add(
                        isinstance(job.partials[i][0], _PARSED_T)
                    )
        if True not in kinds:
            return False
        if kinds == {True} and self._decode_rung() == "device":
            return True
        for job in vq:
            job.lanes = [_lane_to_points(lane) for lane in job.lanes]
        for job in rq:
            for i in range(len(job.msgs)):
                if job.prefail[i] or not isinstance(
                    job.partials[i][0], _PARSED_T
                ):
                    continue
                try:
                    job.partials[i] = [
                        _decode_sig(p.raw) for p in job.partials[i]
                    ]
                except (TblsError, ValueError):
                    job.prefail[i] = True
        return False

    @staticmethod
    def _live_recombine_rows(rq: list[_RecombineJob]):
        ps, msg, sig, gpk, idx = [], [], [], [], []
        for job in rq:
            for i in range(len(job.msgs)):
                if not job.prefail[i]:
                    ps.append(job.pubshares[i])
                    msg.append(job.msgs[i])
                    sig.append(job.partials[i])
                    gpk.append(job.group_pks[i])
                    idx.append(job.indices[i])
        return ps, msg, sig, gpk, idx

    def _pack_flush(self, vq, rq):
        """Decode-pool thread: array packing + RLC randomness for the
        whole flush. Returns (vpack, rpack, pack_span) for _run_device's
        packed fast path — this is the half of the old verify_host/
        recombine_host work that does NOT need the device lane."""
        # pack span = wall-clock trace attribution (FlushStats bridge)
        w0 = time.time()  # lint: allow(monotonic-clock)
        plane = self.plane
        parsed = self._normalize_jobs(vq, rq)
        vpack = None
        flat = self._flat_verify_lanes(vq)
        if flat:
            pks, msgs, sigs = zip(*flat)
            pack = (
                plane.pack_verify_inputs_parsed
                if parsed
                else plane.pack_verify_inputs
            )
            vpack = (
                pack(pks, msgs, sigs),
                plane.make_lane_rand(len(flat)),
                len(flat),
                parsed,
            )
        rpack = None
        ps, msg, sig, gpk, idx = self._live_recombine_rows(rq)
        if msg:
            pack = plane.pack_inputs_parsed if parsed else plane.pack_inputs
            rpack = (
                pack(ps, msg, sig, gpk, idx),
                plane.make_rand(len(msg)),
                len(msg),
                parsed,
            )
        return vpack, rpack, (w0, time.time())  # lint: allow(monotonic-clock)

    # -- device side (worker thread) --------------------------------------

    def _run_device(
        self,
        vq: list[_VerifyJob],
        rq: list[_RecombineJob],
        packed=None,
        window_used: float = 0.0,
        inflight: int = 1,
    ):
        # counters update only AFTER both stages succeed: a failed flush
        # that the degrade rung retries must not double-count its lanes
        t0 = time.monotonic()
        # device span = wall-clock trace attribution; durations use t0
        w0 = time.time()  # lint: allow(monotonic-clock)
        vpack, rpack, pack_span = (
            packed if packed is not None else (None, None, None)
        )
        if packed is None:
            # single-stage flush (pool disabled / pack failed): lane
            # normalization runs here on the device lane instead
            parsed = self._normalize_jobs(vq, rq)
        lanes = 0
        pad_lanes = padded_lanes = 0 if packed is not None else None
        vres: list[list[bool]] = []
        if vq:
            if vpack is not None:
                # flat lane count came with the pack — don't re-flatten
                # on the serialized device lane
                arrays, rand, n, vparsed = vpack
                verify = (
                    self.plane.verify_packed_parsed
                    if vparsed
                    else self.plane.verify_packed
                )
                oks = iter(verify(arrays, rand, n))
                shipped = self._packed_lane_count(arrays)
                pad_lanes += shipped - n
                padded_lanes += shipped
            else:
                flat = self._flat_verify_lanes(vq)
                n = len(flat)
                if flat and parsed:
                    pks, msgs, sigs = zip(*flat)
                    arrays = self.plane.pack_verify_inputs_parsed(
                        pks, msgs, sigs
                    )
                    oks = iter(
                        self.plane.verify_packed_parsed(
                            arrays, self.plane.make_lane_rand(n), n
                        )
                    )
                elif flat:
                    pks, msgs, sigs = zip(*flat)
                    oks = iter(self.plane.verify_host(pks, msgs, sigs))
                else:
                    oks = iter(())
            for job in vq:
                vres.append(
                    [
                        next(oks) if lane is not None else False
                        for lane in job.lanes
                    ]
                )
            lanes += n
        rres: list[tuple[list, list[bool]]] = []
        if rq:
            if rpack is not None:
                arrays, rand, v, rparsed = rpack
                recombine = (
                    self.plane.recombine_packed_parsed
                    if rparsed
                    else self.plane.recombine_packed
                )
                out_sigs, out_oks = recombine(arrays, rand, v)
                shipped = self._packed_lane_count(arrays)
                pad_lanes += shipped - v
                padded_lanes += shipped
            else:
                ps, msg, sig, gpk, idx = self._live_recombine_rows(rq)
                if msg and parsed:
                    args = self.plane.pack_inputs_parsed(
                        ps, msg, sig, gpk, idx
                    )
                    out_sigs, out_oks = self.plane.recombine_packed_parsed(
                        args, self.plane.make_rand(len(msg)), len(msg)
                    )
                elif msg:
                    out_sigs, out_oks = self.plane.recombine_host(
                        ps, msg, sig, gpk, idx
                    )
                else:
                    out_sigs, out_oks = [], []
            it_sig, it_ok = iter(out_sigs), iter(out_oks)
            live_rows = 0
            for job in rq:
                sigs_pts: list = []
                oks: list[bool] = []
                for pf in job.prefail:
                    if pf:
                        sigs_pts.append(None)
                        oks.append(False)
                    else:
                        sigs_pts.append(next(it_sig))
                        oks.append(next(it_ok))
                        live_rows += 1
                rres.append((sigs_pts, oks))
            lanes += live_rows
        mode, cache_n, device_n, python_n = self._decode_breakdown(vq, rq)
        self._account_flush(
            vq,
            rq,
            lanes,
            FlushStats(
                jobs=len(vq) + len(rq),
                lanes=lanes,
                flush_seconds=time.monotonic() - t0,
                window=window_used,
                inflight=inflight,
                pad_lanes=pad_lanes,
                padded_lanes=padded_lanes,
                decode_queue_seconds=self._job_decode_delays(vq, rq),
                decode_mode=mode,
                decode_cache_lanes=cache_n,
                decode_device_lanes=device_n,
                decode_python_lanes=python_n,
                decode_spans=self._job_decode_spans(vq, rq),
                pack_span=pack_span,
                device_span=(w0, time.time()),  # lint: allow(monotonic-clock)
                parents=self._job_parents(vq, rq),
                tenant_lanes=self._job_tenant_lanes(vq, rq),
            ),
        )
        return vres, rres

    @staticmethod
    def _packed_lane_count(arrays) -> int:
        """Leading-axis size of a packed batch = lanes after bucket
        padding (the live mask is the last element of every pack)."""
        live = arrays[-1]
        return int(live.shape[0])

    @staticmethod
    def _job_decode_delays(vq, rq) -> tuple[float, ...]:
        """Decode-pool queue delays of exactly THIS flush's jobs."""
        return tuple(
            delay for job in [*vq, *rq] for delay in job.decode_delays
        )

    @staticmethod
    def _job_decode_spans(vq, rq) -> tuple:
        """Wall-clock decode windows of exactly THIS flush's jobs."""
        return tuple(
            span for job in [*vq, *rq] for span in job.decode_spans
        )

    def _decode_breakdown(self, vq, rq) -> tuple[str, int, int, int]:
        """(mode, cache_lanes, device_lanes, python_lanes) of a flush:
        cache_lanes counts point lookups served by the tpu_impl LRU
        caches (pubkey + message per verify lane; pubshares + message +
        group pubkey per recombine row), device/python_lanes count
        signature lanes by decode rung. The mode is what actually
        shipped; a flush with NO live signature lanes (every lane
        prefailed on host parse) reports the rung in force instead, so
        the tpu_plane_decode_mode gauge never fakes a ladder step-down
        off a fully-malformed window."""
        cache = device = python = 0
        for job in vq:
            for lane in job.lanes:
                if lane is None:
                    continue
                cache += 2
                if isinstance(lane[2], _PARSED_T):
                    device += 1
                else:
                    python += 1
        for job in rq:
            for i, pf in enumerate(job.prefail):
                if pf:
                    continue
                cache += len(job.pubshares[i]) + 2
                if isinstance(job.partials[i][0], _PARSED_T):
                    device += len(job.partials[i])
                else:
                    python += len(job.partials[i])
        if device:
            mode = "device"
        elif python:
            mode = "python"
        else:
            mode = self._decode_live or "python"
        return mode, cache, device, python

    @staticmethod
    def _job_parents(vq, rq) -> tuple:
        """Submitting-span contexts of this flush's jobs (deduped by
        the bridge, ordered by submission)."""
        return tuple(
            job.parent for job in [*vq, *rq] if job.parent is not None
        )

    @staticmethod
    def _job_tenant_lanes(vq, rq) -> tuple:
        """Live lanes per submitting tenant (ISSUE 8). Untagged jobs
        (single-tenant deployments bypassing the service) contribute
        nothing — the aggregate counters already cover them."""
        per: dict[str, int] = {}
        for job in vq:
            if job.tenant is not None:
                per[job.tenant] = per.get(job.tenant, 0) + sum(
                    1 for lane in job.lanes if lane is not None
                )
        for job in rq:
            if job.tenant is not None:
                per[job.tenant] = per.get(job.tenant, 0) + sum(
                    1 for pf in job.prefail if not pf
                )
        return tuple(sorted(per.items()))

    def _account_flush(self, vq, rq, lanes: int, stats: FlushStats) -> None:
        self.lanes_flushed += lanes
        self.flushes += 1
        if stats.pad_lanes:
            self.pad_lanes_flushed += stats.pad_lanes
        if len(vq) + len(rq) >= 2:
            self.coalesced_flushes += 1
        if self.metrics_hook is not None:
            self.metrics_hook(len(vq) + len(rq), lanes)
        if self.stats_hook is not None:
            self.stats_hook(stats)

    async def _decode_stepdown_and_retry(
        self, vq, rq, err, window_used: float = 0.0, inflight: int = 1
    ):
        """Decode-ladder rung (ISSUE 5): a failed flush that shipped
        PARSED lanes steps this coalescer's decode rung down to python
        permanently, converts the batch's parsed signatures to points
        on host, and retries the same batch through the point-input
        programs. Returns (vres, rres) or None when inapplicable (the
        flush wasn't parsed) or the retry itself failed — the caller
        continues down the existing msm-off / host-oracle ladder.

        Applicability is judged by the BATCH (did parsed lanes ship?),
        not by the current rung: with double-buffered windows a second
        in-flight parsed flush can fail AFTER the first one already
        stepped the rung down, and it must still retry here instead of
        burning the process-wide msm-off rung on a decode-family
        failure."""
        if self._closed:
            return None
        parsed = any(
            lane is not None and isinstance(lane[2], _PARSED_T)
            for job in vq
            for lane in job.lanes
        ) or any(
            not pf and isinstance(job.partials[i][0], _PARSED_T)
            for job in rq
            for i, pf in enumerate(job.prefail)
        )
        if not parsed:
            return None
        from charon_tpu.app import log

        log.warn(
            "crypto plane parsed flush failed on device; decode "
            + (
                "stepping down to python"
                if self._decode_live == "device"
                else "rung already stepped down; retrying on python"
            ),
            topic="cryptoplane",
            rung="decode-python",
            err=f"{type(err).__name__}: {str(err)[:160]}",
        )
        self._decode_live = "python"

        def convert_and_run():
            # worker thread: _normalize_jobs sees the stepped-down rung
            # and host-decodes every parsed lane before the device pass
            return self._run_device(vq, rq, None, window_used, inflight)

        try:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(self._executor, convert_and_run)
        except Exception:  # noqa: BLE001 — continue down the ladder
            return None

    async def _degrade_and_retry(
        self, vq, rq, err, window_used: float = 0.0, inflight: int = 1
    ):
        """One-shot msm-off rung: flip the MSM family off, rebuild the
        plane so its programs re-trace, and retry the SAME batch on the
        per-lane path. Returns (vres, rres) or None if the rung is spent
        / inapplicable / the retry also failed."""
        from charon_tpu.ops import blsops
        from charon_tpu.ops import msm as MSM

        if isinstance(
            err,
            (TypeError, ValueError, KeyError, IndexError,
             AttributeError, AssertionError, TblsError),
        ):
            # host-side bug classes (shape/tracing/logic errors): the
            # per-lane path would hit the same bug, and permanently
            # disabling the process-wide MSM fast path + paying a
            # minutes-long plane rebuild on the duty path buys nothing
            # (ADVICE r4: gate the rung on device/compile error types)
            return None
        if (
            self._closed
            or self._degraded
            or not MSM.msm_active()
            or self._plane_factory is None
        ):
            # no factory -> no retry: the plane's jitted programs are
            # per-instance, so without a rebuild the retry would re-run
            # the identical failed executable
            return None
        self._degraded = True
        from charon_tpu.app import log

        log.warn(
            "crypto plane flush failed on device; degrading",
            topic="cryptoplane",
            rung="msm-off",
            err=f"{type(err).__name__}: {str(err)[:160]}",
        )
        MSM.set_msm(False)
        blsops.clear_kernel_caches()

        def rebuild_and_run():
            # worker thread, NOT the event loop: the factory touches
            # jax.devices()/compilation, which can block for minutes on
            # a wedged device claim
            self.plane = self._plane_factory()
            return self._run_device(vq, rq, None, window_used, inflight)

        try:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(self._executor, rebuild_and_run)
        except Exception:  # noqa: BLE001 — rung spent; caller fails waiters
            return None

    # -- pre-warm (startup) ------------------------------------------------

    async def prewarm(
        self,
        verify_lanes: Sequence[int] | None = None,
        recombine_lanes: Sequence[int] | None = None,
    ) -> list:
        """Trace + compile the canonical duty-path shapes on the device
        lane so the first live slot never eats a cold pairing compile.
        None defers to the plane's bucket-ladder defaults (smallest
        bucket + canonical burst shapes). Runs through the same
        serialized executor as flushes (a live flush queues behind the
        compile instead of racing it). Returns the plane's
        [(kind, lanes, seconds)] compile report; [] when the plane has
        no prewarm support (test fakes)."""
        fn = getattr(self.plane, "prewarm", None)
        if fn is None:
            return []
        kwargs = {}
        if self._decode_rung() == "device":
            # also compile the decode-fused program family — live
            # flushes on the device rung land on those shapes
            import inspect

            if "decompress" in inspect.signature(fn).parameters:
                kwargs["decompress"] = True
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor,
            lambda: fn(
                verify_lanes=(
                    None if verify_lanes is None else tuple(verify_lanes)
                ),
                recombine_lanes=(
                    None
                    if recombine_lanes is None
                    else tuple(recombine_lanes)
                ),
                **kwargs,
            ),
        )

    # -- bulk cache warm-up (ISSUE 6) --------------------------------------

    def _plane_has_warm_api(self) -> bool:
        return all(
            hasattr(self.plane, name)
            for name in ("hash_to_g2_host", "decompress_g1_host")
        )

    def _warm_sync(
        self, pubkeys: list, messages: list, chunk: int | None
    ) -> dict:
        """Worker-thread body of warm_caches: bulk-decode through the
        plane's sharded warm programs (device rung) or per-point host
        decode (python rung / jax-less host), feeding the tpu_impl
        point caches via PointCache.put."""
        try:
            from charon_tpu.tbls import tpu_impl
        except Exception:  # pragma: no cover — jax-less host without
            # the tbls device backend: there are no point caches to
            # warm; report the skip instead of failing startup
            return {
                "pubkey": {"skipped": len(pubkeys)},
                "message": {"skipped": len(messages)},
                "seconds": 0.0,
            }
        device = (
            self._decode_rung() == "device" and self._plane_has_warm_api()
        )
        plane = self.plane

        class _PlaneWarmEngine:
            """Adapter: the plane's sharded warm programs behind the
            BlsEngine bulk-decode surface warm_point_caches drives."""

            @staticmethod
            def decompress_g1_batch(batch, subgroup_check=True):
                return plane.decompress_g1_host(batch)

            @staticmethod
            def hash_to_g2_batch(batch):
                return plane.hash_to_g2_host(batch)

        return tpu_impl.warm_point_caches(
            pubkeys=pubkeys,
            messages=messages,
            engine=_PlaneWarmEngine() if device else None,
            device=device,
            # None = inherit tpu_impl.WARMUP_CHUNK — one default for
            # every warm path, documented in docs/operations.md
            chunk=chunk if chunk is not None else tpu_impl.WARMUP_CHUNK,
        )

    async def warm_caches(
        self,
        pubkeys: Sequence[bytes] = (),
        messages: Sequence[bytes] = (),
        chunk: int | None = None,
    ) -> dict:
        """Bulk-populate the point caches for a key/message set — the
        startup and validator-set-rotation hook (ISSUE 6). On the
        device decode rung the field work (G1 decompression with the
        GLV subgroup check, hash-to-curve SSWU + isogeny + psi cofactor
        clearing) runs as chunked sharded device programs; the python
        rung decodes per point on host (still off the event loop).

        Runs on its OWN short-lived worker thread, NEVER the serialized
        device lane: a live flush racing a warm-up must not queue
        behind thousands of warm lanes (device dispatches interleave in
        XLA's stream; host stages run in parallel). Idempotent — keys
        already cached are skipped — so a rotation re-warm costs only
        the new entries. Returns the per-cache stats dict and feeds it
        to `warmup_hook`."""
        import concurrent.futures

        loop = asyncio.get_running_loop()
        ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="crypto-warmup"
        )
        try:
            stats = await loop.run_in_executor(
                ex,
                self._warm_sync,
                list(pubkeys),
                list(messages),
                chunk,
            )
        finally:
            ex.shutdown(wait=False)
        self.warmups += 1
        self.warmup_lanes += sum(
            n
            for cache in ("pubkey", "message")
            for src, n in stats.get(cache, {}).items()
            if src in ("device", "python")
        )
        if self.warmup_hook is not None:
            self.warmup_hook(stats)
        return stats

    # -- python-spec host fallback (worker thread) -------------------------

    @staticmethod
    def _oracle_verify_lane(pk_pt, msg_pt, sig_pt) -> bool:
        from charon_tpu.crypto.bls import G1_GEN, g1_neg
        from charon_tpu.crypto.pairing_fast import (
            is_gt_one,
            multi_pairing_fast,
        )

        return is_gt_one(
            multi_pairing_fast([(sig_pt, g1_neg(G1_GEN)), (msg_pt, pk_pt)])
        )

    def _run_host_oracle(self, vq: list[_VerifyJob], rq: list[_RecombineJob]):
        """Serve the SAME batch shape as _run_device on the pure-python
        spec backend (crypto/bls + crypto/shamir): per-lane pairing
        verify and Lagrange recombination on decoded points. No device,
        no jitted programs — the rung below every accelerator failure."""
        from charon_tpu.crypto import shamir

        t0 = time.monotonic()
        w0 = time.time()  # lint: allow(monotonic-clock) — device span is trace attribution
        # a parsed flush can land here when every device rung failed:
        # force the python lane representation first (worker thread —
        # the bigint decompression belongs here, not the event loop)
        self._decode_live = "python"
        self._normalize_jobs(vq, rq)
        lanes = 0
        vres: list[list[bool]] = []
        for job in vq:
            out = []
            for lane in job.lanes:
                if lane is None:
                    out.append(False)
                    continue
                out.append(self._oracle_verify_lane(*lane))
                lanes += 1
            vres.append(out)
        rres: list[tuple[list, list[bool]]] = []
        for job in rq:
            sigs_pts: list = []
            oks: list[bool] = []
            for i, pf in enumerate(job.prefail):
                if pf:
                    sigs_pts.append(None)
                    oks.append(False)
                    continue
                group_sig = shamir.threshold_aggregate_g2(
                    dict(zip(job.indices[i], job.partials[i]))
                )
                ok = self._oracle_verify_lane(
                    job.group_pks[i], job.msgs[i], group_sig
                )
                sigs_pts.append(group_sig)
                oks.append(ok)
                lanes += 1
            rres.append((sigs_pts, oks))
        mode, cache_n, device_n, python_n = self._decode_breakdown(vq, rq)
        self._account_flush(
            vq,
            rq,
            lanes,
            FlushStats(
                jobs=len(vq) + len(rq),
                lanes=lanes,
                flush_seconds=time.monotonic() - t0,
                window=self._window_current,
                inflight=self._inflight,
                pad_lanes=None,
                padded_lanes=None,
                decode_queue_seconds=self._job_decode_delays(vq, rq),
                fallback=True,
                decode_mode=mode,
                decode_cache_lanes=cache_n,
                decode_device_lanes=device_n,
                decode_python_lanes=python_n,
                decode_spans=self._job_decode_spans(vq, rq),
                device_span=(w0, time.time()),  # lint: allow(monotonic-clock)
                parents=self._job_parents(vq, rq),
                tenant_lanes=self._job_tenant_lanes(vq, rq),
            ),
        )
        return vres, rres
