"""Slot-tick coalescer: ONE sharded device program per flush for the
whole node's concurrent crypto work.

The reference executes crypto per duty per signature on the CPU as calls
arrive (ref: core/sigagg/sigagg.go:84-122 per-pubkey ThresholdAggregate +
verify; core/parsigex/parsigex.go:94-98 and
core/validatorapi/validatorapi.go:1213 per-signature herumi verifies).
A TPU inverts the economics: launching a program costs milliseconds while
extra lanes in a launched batch cost microseconds — so the win is
batching ACROSS concurrent duties, not just within one (SURVEY §7 step 4;
VERDICT r3 next-step 3).

SlotCoalescer is that batching point. Components submit work from the
event loop and await results; submissions arriving within one coalescing
window (default 20 ms — negligible against a 12 s slot, wide enough to
catch the burst of partial-sig arrivals and duty expiries a slot tick
produces) are merged:

  * verify lanes (pk, root, sig) from ParSigEx inbound sets, the
    ValidatorAPI's pubshare checks, and SigAgg — concatenated into one
    sharded RLC verify (`SlotCryptoPlane.verify_host`);
  * threshold recombination jobs [V, t] from SigAgg — concatenated along
    the validator axis into one sharded recombine+verify step
    (`SlotCryptoPlane.recombine_host`).

Device programs run on a worker thread so the event loop keeps serving
QBFT/p2p traffic while the accelerator works. Decode failures (malformed
compressed points) never reach the device: those lanes fail on host and
are replaced by lane-0 padding in the batch.

The plane object only needs `t`, `verify_host`, and `recombine_host` —
production passes `parallel.mesh.SlotCryptoPlane`; fast-tier tests pass
a counting fake backed by the pure-python oracle.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Sequence

from charon_tpu.crypto import g1g2
from charon_tpu.tbls import TblsError


@dataclass
class _VerifyJob:
    lanes: list  # [(pk_pt, msg_pt, sig_pt) | None] — None = host decode fail
    fut: asyncio.Future = field(default=None)  # type: ignore[assignment]


@dataclass
class _RecombineJob:
    # all rows [V][t] / [V]; lanes with decode failures are pre-failed
    pubshares: list
    msgs: list
    partials: list
    group_pks: list
    indices: list
    prefail: list  # [V] bool — True: fail without consulting the device
    fut: asyncio.Future = field(default=None)  # type: ignore[assignment]


def _decode_pubkey(pk: bytes):
    from charon_tpu.tbls.tpu_impl import _cached_pubkey_point

    return _cached_pubkey_point(pk)


def _decode_sig(sig: bytes):
    from charon_tpu.tbls.python_impl import sig_to_point

    pt = sig_to_point(sig, subgroup_check=False)
    if pt is None:
        raise TblsError("infinite signature")
    return pt


def _msg_point(root: bytes):
    from charon_tpu.tbls.tpu_impl import _cached_msg_point

    return _cached_msg_point(root)


class SlotCoalescer:
    """Merges concurrent verify / recombine submissions into single
    sharded device programs (see module docstring).

    window: seconds to wait after the first submission before flushing.
    flushes / coalesced_flushes / lanes_flushed: observability counters
    (exported as node metrics by app/run.py).
    """

    def __init__(
        self, plane, window: float = 0.02, metrics_hook=None, plane_factory=None
    ):
        import concurrent.futures

        self.plane = plane
        self.window = window
        # msm-off degradation rung (mirrors tbls/tpu_impl._rlc_guarded):
        # a device/compile failure in the newest kernel family is not a
        # crypto verdict. plane_factory() rebuilds the plane after the
        # flag flip so its jitted programs re-trace; without a factory
        # there is no degrade at all (the flag stays untouched — a retry
        # without a rebuild would re-run the identical failed executable).
        self._plane_factory = plane_factory
        self._degraded = False
        self._verify_q: list[_VerifyJob] = []
        self._recombine_q: list[_RecombineJob] = []
        self._flush_task: asyncio.Task | None = None
        # single-threaded: a second window can elapse while a device
        # program is still running; its flush must QUEUE behind the
        # first, not race it (device contention + counter integrity)
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="crypto-plane"
        )
        self.flushes = 0
        self.coalesced_flushes = 0  # flushes that merged >= 2 jobs
        self.lanes_flushed = 0
        self.host_fallback_flushes = 0  # served by the python-spec rung
        # called after each flush with (jobs, lanes) — thread-safe
        # counters only (runs on the device worker thread)
        self.metrics_hook = metrics_hook

    @property
    def t(self) -> int:
        return self.plane.t

    # -- submission APIs (event-loop side) --------------------------------

    async def verify(
        self, items: Sequence[tuple[bytes, bytes, bytes]]
    ) -> list[bool]:
        """Batch-verify (pubkey_bytes, signing_root, sig_bytes) lanes.
        Returns per-lane validity; malformed encodings are False."""
        if not items:
            return []
        lanes: list = []
        for pk, root, sig in items:
            try:
                lanes.append(
                    (_decode_pubkey(pk), _msg_point(root), _decode_sig(sig))
                )
            except (TblsError, ValueError):
                lanes.append(None)
        job = _VerifyJob(lanes=lanes)
        job.fut = asyncio.get_running_loop().create_future()
        self._verify_q.append(job)
        self._arm()
        return await job.fut

    async def recombine(
        self,
        pubshares: Sequence[Sequence[bytes]],
        roots: Sequence[bytes],
        partials: Sequence[Sequence[bytes]],
        group_pks: Sequence[bytes],
        indices: Sequence[Sequence[int]],
    ) -> tuple[list[bytes | None], list[bool]]:
        """Threshold-recombine + verify a duty's [V, t] workload.
        Returns ([V] group signature bytes or None, [V] ok flags)."""
        if not roots:
            return [], []
        t = self.plane.t
        ps_rows, msg_pts, sig_rows, gpk_pts, idx_rows, prefail = (
            [], [], [], [], [], []
        )
        for ps_row, root, sig_row, gpk, idx_row in zip(
            pubshares, roots, partials, group_pks, indices
        ):
            try:
                if len(sig_row) != t or len(ps_row) != t or len(idx_row) != t:
                    raise TblsError(f"need exactly t={t} partials per lane")
                if any(i <= 0 for i in idx_row):
                    raise TblsError("share indices are 1-based")
                ps_rows.append([_decode_pubkey(p) for p in ps_row])
                sig_rows.append([_decode_sig(s) for s in sig_row])
                gpk_pts.append(_decode_pubkey(gpk))
                msg_pts.append(_msg_point(root))
                idx_rows.append(list(idx_row))
                prefail.append(False)
            except (TblsError, ValueError):
                # placeholder row (patched to lane data below) — never
                # consulted; the lane is failed on host
                ps_rows.append(None)
                sig_rows.append(None)
                gpk_pts.append(None)
                msg_pts.append(None)
                idx_rows.append(None)
                prefail.append(True)
        job = _RecombineJob(
            pubshares=ps_rows,
            msgs=msg_pts,
            partials=sig_rows,
            group_pks=gpk_pts,
            indices=idx_rows,
            prefail=prefail,
        )
        job.fut = asyncio.get_running_loop().create_future()
        self._recombine_q.append(job)
        self._arm()
        sigs_pts, oks = await job.fut
        return (
            [
                g1g2.g2_to_bytes(pt) if pt is not None else None
                for pt in sigs_pts
            ],
            oks,
        )

    # -- flush machinery ---------------------------------------------------

    def _arm(self) -> None:
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.create_task(self._flush_after_window())

    async def _flush_after_window(self) -> None:
        await asyncio.sleep(self.window)
        vq, self._verify_q = self._verify_q, []
        rq, self._recombine_q = self._recombine_q, []
        # new submissions from here on arm a fresh flush task
        self._flush_task = None
        if not vq and not rq:
            return
        loop = asyncio.get_running_loop()
        try:
            vres, rres = await loop.run_in_executor(
                self._executor, self._run_device, vq, rq
            )
        except Exception as e:  # noqa: BLE001 — degrade, else fail waiters
            retried = await self._degrade_and_retry(vq, rq, e)
            if retried is None:
                # last rung: the pure-python spec oracle. Orders of
                # magnitude slower than the device, but a wedged
                # accelerator must cost latency, not the duty — the
                # signing plane stays live on the degraded backend
                # (ISSUE: degrade TPU -> native -> python-spec).
                try:
                    retried = await loop.run_in_executor(
                        self._executor, self._run_host_oracle, vq, rq
                    )
                    self.host_fallback_flushes += 1
                    from charon_tpu.app import log

                    log.warn(
                        "crypto plane flush served by python-spec "
                        "host fallback",
                        topic="cryptoplane",
                        rung="host-oracle",
                        err=f"{type(e).__name__}: {str(e)[:160]}",
                    )
                except Exception:  # noqa: BLE001 — rungs exhausted
                    for job in [*vq, *rq]:
                        if not job.fut.done():
                            job.fut.set_exception(
                                TblsError(f"crypto plane flush failed: {e}")
                            )
                    return
            vres, rres = retried
        for job, res in zip(vq, vres):
            if not job.fut.done():
                job.fut.set_result(res)
        for job, res in zip(rq, rres):
            if not job.fut.done():
                job.fut.set_result(res)

    async def _degrade_and_retry(self, vq, rq, err):
        """One-shot msm-off rung: flip the MSM family off, rebuild the
        plane so its programs re-trace, and retry the SAME batch on the
        per-lane path. Returns (vres, rres) or None if the rung is spent
        / inapplicable / the retry also failed."""
        from charon_tpu.ops import blsops
        from charon_tpu.ops import msm as MSM

        if isinstance(
            err,
            (TypeError, ValueError, KeyError, IndexError,
             AttributeError, AssertionError, TblsError),
        ):
            # host-side bug classes (shape/tracing/logic errors): the
            # per-lane path would hit the same bug, and permanently
            # disabling the process-wide MSM fast path + paying a
            # minutes-long plane rebuild on the duty path buys nothing
            # (ADVICE r4: gate the rung on device/compile error types)
            return None
        if (
            self._degraded
            or not MSM.msm_active()
            or self._plane_factory is None
        ):
            # no factory -> no retry: the plane's jitted programs are
            # per-instance, so without a rebuild the retry would re-run
            # the identical failed executable
            return None
        self._degraded = True
        from charon_tpu.app import log

        log.warn(
            "crypto plane flush failed on device; degrading",
            topic="cryptoplane",
            rung="msm-off",
            err=f"{type(err).__name__}: {str(err)[:160]}",
        )
        MSM.set_msm(False)
        blsops.clear_kernel_caches()

        def rebuild_and_run():
            # worker thread, NOT the event loop: the factory touches
            # jax.devices()/compilation, which can block for minutes on
            # a wedged device claim
            self.plane = self._plane_factory()
            return self._run_device(vq, rq)

        try:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(self._executor, rebuild_and_run)
        except Exception:  # noqa: BLE001 — rung spent; caller fails waiters
            return None

    # -- device side (worker thread) --------------------------------------

    def _run_device(self, vq: list[_VerifyJob], rq: list[_RecombineJob]):
        # counters update only AFTER both stages succeed: a failed flush
        # that the degrade rung retries must not double-count its lanes
        lanes = 0
        vres: list[list[bool]] = []
        if vq:
            flat: list = []
            for job in vq:
                flat.extend(l for l in job.lanes if l is not None)
            if flat:
                pks, msgs, sigs = zip(*flat)
                oks = iter(self.plane.verify_host(pks, msgs, sigs))
            else:
                oks = iter(())
            for job in vq:
                vres.append(
                    [
                        next(oks) if l is not None else False
                        for l in job.lanes
                    ]
                )
            lanes += len(flat)
        rres: list[tuple[list, list[bool]]] = []
        if rq:
            ps, msg, sig, gpk, idx = [], [], [], [], []
            for job in rq:
                for i in range(len(job.msgs)):
                    if not job.prefail[i]:
                        ps.append(job.pubshares[i])
                        msg.append(job.msgs[i])
                        sig.append(job.partials[i])
                        gpk.append(job.group_pks[i])
                        idx.append(job.indices[i])
            if msg:
                out_sigs, out_oks = self.plane.recombine_host(
                    ps, msg, sig, gpk, idx
                )
            else:
                out_sigs, out_oks = [], []
            it_sig, it_ok = iter(out_sigs), iter(out_oks)
            for job in rq:
                sigs_pts: list = []
                oks: list[bool] = []
                for pf in job.prefail:
                    if pf:
                        sigs_pts.append(None)
                        oks.append(False)
                    else:
                        sigs_pts.append(next(it_sig))
                        oks.append(next(it_ok))
                rres.append((sigs_pts, oks))
            lanes += len(msg)
        self.lanes_flushed += lanes
        self.flushes += 1
        if len(vq) + len(rq) >= 2:
            self.coalesced_flushes += 1
        if self.metrics_hook is not None:
            self.metrics_hook(len(vq) + len(rq), lanes)
        return vres, rres

    # -- python-spec host fallback (worker thread) -------------------------

    @staticmethod
    def _oracle_verify_lane(pk_pt, msg_pt, sig_pt) -> bool:
        from charon_tpu.crypto.bls import G1_GEN, g1_neg
        from charon_tpu.crypto.pairing_fast import (
            is_gt_one,
            multi_pairing_fast,
        )

        return is_gt_one(
            multi_pairing_fast([(sig_pt, g1_neg(G1_GEN)), (msg_pt, pk_pt)])
        )

    def _run_host_oracle(self, vq: list[_VerifyJob], rq: list[_RecombineJob]):
        """Serve the SAME batch shape as _run_device on the pure-python
        spec backend (crypto/bls + crypto/shamir): per-lane pairing
        verify and Lagrange recombination on decoded points. No device,
        no jitted programs — the rung below every accelerator failure."""
        from charon_tpu.crypto import shamir

        lanes = 0
        vres: list[list[bool]] = []
        for job in vq:
            out = []
            for lane in job.lanes:
                if lane is None:
                    out.append(False)
                    continue
                out.append(self._oracle_verify_lane(*lane))
                lanes += 1
            vres.append(out)
        rres: list[tuple[list, list[bool]]] = []
        for job in rq:
            sigs_pts: list = []
            oks: list[bool] = []
            for i, pf in enumerate(job.prefail):
                if pf:
                    sigs_pts.append(None)
                    oks.append(False)
                    continue
                group_sig = shamir.threshold_aggregate_g2(
                    dict(zip(job.indices[i], job.partials[i]))
                )
                ok = self._oracle_verify_lane(
                    job.group_pks[i], job.msgs[i], group_sig
                )
                sigs_pts.append(group_sig)
                oks.append(ok)
                lanes += 1
            rres.append((sigs_pts, oks))
        self.lanes_flushed += lanes
        self.flushes += 1
        if len(vq) + len(rq) >= 2:
            self.coalesced_flushes += 1
        if self.metrics_hook is not None:
            self.metrics_hook(len(vq) + len(rq), lanes)
        return vres, rres
