"""Tracker: per-duty failure detection, partial-signature consistency,
and peer participation.

Mirrors ref: core/tracker — every workflow component emits an event per
duty step (step enum tracker.go:20-34); when the Deadliner expires a duty
the tracker determines the first failing step and a reason
(tracker.go:154, reasons reason.go), groups the observed partial
signatures by message root per pubkey to detect inconsistent partials
(tracker.go:59-71 parsigsByMsg + MsgRootsConsistent, metrics.go:85
inconsistent_parsigs_total), and reports per-peer participation counts
plus UNEXPECTED peers — shares that submitted partials for a duty that
was never scheduled for that validator (tracker.go:539-573
analyseParticipation).

Wiring: `tracking(tracker)` is a wire() option that wraps every
subscription edge (ref: core/tracking.go wraps via core.WithTracking).
"""

from __future__ import annotations

import enum
import math
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from charon_tpu.core.types import Duty, DutyType, PubKey


class Step(enum.IntEnum):
    """Workflow steps in pipeline order (ref: core/tracker/tracker.go:20)."""

    SCHEDULER = 0
    FETCHER = 1
    CONSENSUS = 2
    DUTY_DB = 3
    VALIDATOR_API = 4
    PARSIG_DB_INTERNAL = 5
    PARSIG_EX = 6
    PARSIG_DB_THRESHOLD = 7
    SIG_AGG = 8
    AGG_SIG_DB = 9
    BCAST = 10
    # post-broadcast on-chain verification, fed by the InclusionChecker
    # (ref: tracker.go chainInclusion step + InclusionChecked input)
    CHAIN_INCLUSION = 11

    def __str__(self) -> str:
        return self.name.lower()


# Map wire() edge names to the steps their completion proves. An edge
# firing proves the *previous* step delivered (e.g. fetcher.fetch being
# invoked proves the scheduler emitted the duty).
_EDGE_STEPS: dict[str, tuple[Step, ...]] = {
    "fetcher.fetch": (Step.SCHEDULER, Step.FETCHER),
    "consensus.propose": (Step.CONSENSUS,),
    "dutydb.store": (Step.DUTY_DB,),
    "parsigdb.store_internal": (Step.VALIDATOR_API, Step.PARSIG_DB_INTERNAL),
    "parsigex.broadcast": (Step.PARSIG_EX,),
    "parsigdb.store_external": (Step.PARSIG_EX,),
    "sigagg.aggregate": (Step.PARSIG_DB_THRESHOLD, Step.SIG_AGG),
    "aggsigdb.store": (Step.AGG_SIG_DB,),
    "broadcaster.broadcast": (Step.BCAST,),
}


class Reason(str, enum.Enum):
    """Failure reasons with ref-parity codes (ref: core/tracker/reason.go
    — each reason there carries Code/Short/Long; the enum VALUE here is
    the code, `describe()` the operator-facing text)."""

    NOT_SCHEDULED = "not_scheduled"
    FETCH_BN_ERROR = "fetch_bn_error"
    FETCH_FAILED = "bug_fetch_error"
    RANDAO_FAILED = "randao_failed"
    PREPARE_AGGREGATOR_FAILED = "prepare_aggregator_failed"
    PREPARE_SYNC_CONTRIBUTION_FAILED = "prepare_sync_contribution_failed"
    NO_CONSENSUS = "no_consensus"
    NO_LOCAL_PARTIAL = "no_local_vc_signature"
    NO_PEER_SIGNATURES = "no_peer_signatures"
    INSUFFICIENT_PARTIALS = "insufficient_peer_signatures"
    PARSIG_INCONSISTENT = "bug_par_sig_db_inconsistent"
    PARSIG_INCONSISTENT_SYNC = "par_sig_db_inconsistent_sync"
    AGGREGATION_FAILED = "bug_sig_agg"
    BROADCAST_FAILED = "broadcast_bn_error"
    NOT_INCLUDED = "not_included_onchain"
    UNKNOWN = "unknown"

    def describe(self) -> str:
        return _REASON_TEXT[self]


_REASON_TEXT = {
    Reason.NOT_SCHEDULED: "duty was never scheduled",
    Reason.FETCH_BN_ERROR: "the beacon node returned an error fetching duty data",
    Reason.FETCH_FAILED: "failed to fetch duty data from the beacon node",
    Reason.RANDAO_FAILED: "the proposal could not be fetched because the randao duty failed",
    Reason.PREPARE_AGGREGATOR_FAILED: "the aggregation could not start because the prepare-aggregator duty failed",
    Reason.PREPARE_SYNC_CONTRIBUTION_FAILED: "the contribution could not start because the prepare-sync-contribution duty failed",
    Reason.NO_CONSENSUS: "consensus was not reached",
    Reason.NO_LOCAL_PARTIAL: "validator client did not submit a partial signature",
    Reason.NO_PEER_SIGNATURES: "no partial signatures received from peers",
    Reason.INSUFFICIENT_PARTIALS: "insufficient partial signatures from peers",
    Reason.PARSIG_INCONSISTENT: "bug: inconsistent partial signatures received",
    Reason.PARSIG_INCONSISTENT_SYNC: "known limitation: inconsistent sync committee signatures received",
    Reason.AGGREGATION_FAILED: "threshold aggregation or verification failed",
    Reason.BROADCAST_FAILED: "failed to broadcast to the beacon node",
    Reason.NOT_INCLUDED: "broadcast duty was never included on-chain",
    Reason.UNKNOWN: "unexpected failure",
}


_FAIL_REASONS = {
    Step.SCHEDULER: Reason.NOT_SCHEDULED,
    Step.FETCHER: Reason.FETCH_FAILED,
    Step.CONSENSUS: Reason.NO_CONSENSUS,
    Step.DUTY_DB: Reason.NO_LOCAL_PARTIAL,
    Step.VALIDATOR_API: Reason.NO_LOCAL_PARTIAL,
    Step.PARSIG_DB_INTERNAL: Reason.INSUFFICIENT_PARTIALS,
    Step.PARSIG_EX: Reason.NO_PEER_SIGNATURES,
    Step.PARSIG_DB_THRESHOLD: Reason.INSUFFICIENT_PARTIALS,
    Step.SIG_AGG: Reason.AGGREGATION_FAILED,
    Step.AGG_SIG_DB: Reason.AGGREGATION_FAILED,
    Step.BCAST: Reason.BROADCAST_FAILED,
    Step.CHAIN_INCLUSION: Reason.NOT_INCLUDED,
}

# Duty types whose partial signatures legitimately disagree across peers
# (each sync-committee member may see a different head — ref: tracker.go
# expectInconsistentParSigs).
_EXPECT_INCONSISTENT = {DutyType.SYNC_MESSAGE, DutyType.SYNC_CONTRIBUTION}

# VC-triggered duties with no locally scheduled definition — their
# partials can never be classified unexpected (ref: tracker.go
# isParSigEventExpected: DutyExit / DutyBuilderRegistration).
_UNSCHEDULED_TYPES = {
    DutyType.EXIT,
    DutyType.BUILDER_REGISTRATION,
    DutyType.SIGNATURE,
}

# Terminal step per duty type: most duties end at broadcast, but the
# internal aggregate-only duties (randao, the two selection-proof
# prepares) complete at the aggregate store and never broadcast
# (ref: tracker.go step expectations per duty type).
_TERMINAL_STEPS = {
    DutyType.RANDAO: Step.AGG_SIG_DB,
    DutyType.PREPARE_AGGREGATOR: Step.AGG_SIG_DB,
    DutyType.PREPARE_SYNC_CONTRIBUTION: Step.AGG_SIG_DB,
    # protocol-internal negotiation completes at consensus decision
    # (its value never enters the signing pipeline)
    DutyType.INFO_SYNC: Step.CONSENSUS,
}

# Duties whose fetch depends on a prerequisite duty in the same slot
# (ref: tracker.go analyseFetcherFailedProposer/-Aggregator/-SyncContribution).
_FETCH_PREREQ = {
    DutyType.PROPOSER: (DutyType.RANDAO, Reason.RANDAO_FAILED),
    DutyType.AGGREGATOR: (
        DutyType.PREPARE_AGGREGATOR,
        Reason.PREPARE_AGGREGATOR_FAILED,
    ),
    DutyType.SYNC_CONTRIBUTION: (
        DutyType.PREPARE_SYNC_CONTRIBUTION,
        Reason.PREPARE_SYNC_CONTRIBUTION_FAILED,
    ),
}
_PREREQ_TYPES = frozenset(p for p, _ in _FETCH_PREREQ.values())


@dataclass
class DutyReport:
    duty: Duty
    success: bool
    failed_step: Step | None
    reason: Reason | None
    participation: dict[int, bool]  # share_idx -> partial sig seen
    errors: list[str] = field(default_factory=list)
    # per-share dedup'd (pubkey, share) participation counts and the
    # expected count per peer (== number of scheduled validators)
    participation_counts: dict[int, int] = field(default_factory=dict)
    expected_per_peer: int = 0
    # share_idx -> number of partials for validators with no scheduled
    # duty (ref: analyseParticipation unexpectedShares)
    unexpected_shares: dict[int, int] = field(default_factory=dict)
    # pubkeys whose partials arrived under more than one message root
    inconsistent_pubkeys: list[PubKey] = field(default_factory=list)
    # per-validator attribution (ref: the reference tracks events per
    # (duty, pubkey) and reports each validator's failure separately):
    # expected pubkeys whose partial signatures never reached the
    # cluster threshold — populated even when the duty as a whole
    # succeeded for the other validators (partial success)
    failed_pubkeys: dict[PubKey, Reason] = field(default_factory=dict)
    # the duty's deterministic trace id (app/tracer.duty_trace_id):
    # the report's handle into /debug/traces and /debug/duty/<slot>
    trace_id: str = ""


ReportSub = Callable[[DutyReport], Awaitable[None] | None]


def _parsig_root(psig) -> bytes:
    """Message root of a ParSignedData for consistency grouping —
    delegates to the object's own message_root() (also used by parsigdb
    when grouping the same partial). The fallback digests ONLY the kind
    and payload: hashing anything containing the per-share signature
    would give every peer a unique root and flag consistent duties as
    inconsistent."""
    try:
        return psig.message_root()
    except Exception:  # noqa: BLE001 — never let tracking break the flow
        import hashlib

        sd = getattr(psig, "data", psig)
        return hashlib.sha256(
            repr((getattr(sd, "kind", None), getattr(sd, "payload", sd))).encode()
        ).digest()


class Tracker:
    """threshold/peers: for participation accounting."""

    def __init__(
        self, peer_share_indices: list[int], threshold: int | None = None
    ) -> None:
        self.peer_share_indices = list(peer_share_indices)
        # partial-signature count an expected validator needs; defaults
        # to the BFT quorum of the peer count
        self.threshold = threshold or math.ceil(
            2 * len(peer_share_indices) / 3
        )
        self._steps: dict[Duty, set[Step]] = defaultdict(set)
        self._errors: dict[Duty, list[str]] = defaultdict(list)
        # duty -> pubkey -> msg root -> set of share indices
        # (ref: tracker.go parsigsByMsg)
        self._parsigs: dict[Duty, dict[PubKey, dict[bytes, set[int]]]] = (
            defaultdict(lambda: defaultdict(lambda: defaultdict(set)))
        )
        # duty -> pubkeys with a locally scheduled definition
        self._expected: dict[Duty, set[PubKey]] = defaultdict(set)
        # outcome memory for prerequisite analysis (randao -> proposer):
        # expiry order within a slot is not guaranteed, so both failure
        # AND success of already-analysed prerequisites are remembered
        self._failed_steps: dict[Duty, Step] = {}
        self._completed: dict[Duty, None] = {}  # insertion-ordered set
        self._subs: list[ReportSub] = []
        # counters (exported through app/metrics + monitoring endpoint)
        self.failed_total: dict[tuple, int] = defaultdict(int)
        self.success_total: dict[Duty, int] = {}
        self.participation_total: dict[int, int] = defaultdict(int)
        self.inconsistent_total: dict[DutyType, int] = defaultdict(int)
        self.unexpected_total: dict[int, int] = defaultdict(int)
        self.inclusion_included_total: dict[DutyType, int] = defaultdict(int)
        self.inclusion_missed_total: dict[DutyType, int] = defaultdict(int)
        self.pubkey_failures_total: dict[DutyType, int] = defaultdict(int)

    def subscribe(self, sub: ReportSub) -> None:
        self._subs.append(sub)

    # -- event intake -----------------------------------------------------

    def step_event(self, duty: Duty, step: Step) -> None:
        self._steps[duty].add(step)

    def step_failed(self, duty: Duty, step: Step, err: Exception) -> None:
        self._errors[duty].append(f"{step}: {err}")

    def duty_scheduled(self, duty: Duty, pubkeys) -> None:
        """Record which validators this duty was scheduled for — the
        baseline for unexpected-peer detection."""
        self._expected[duty].update(pubkeys)

    def partial_observed(
        self, duty: Duty, share_idx: int, pubkey=None, root: bytes | None = None
    ) -> None:
        self._parsigs[duty][pubkey][root or b""].add(share_idx)

    def inclusion_checked(self, duty: Duty, pubkey, included: bool) -> None:
        """Post-broadcast on-chain result from the InclusionChecker.

        Arrives up to INCL_MISSED_LAG slots after the duty — long past its
        deadline analysis — so it feeds the standalone chain-inclusion
        counters rather than the per-duty report (ref: tracker.go:815
        InclusionChecked feeds a chainInclusion step event).
        """
        if included:
            self.inclusion_included_total[duty.type] += 1
        else:
            self.inclusion_missed_total[duty.type] += 1
            # same (type, step) key shape as every other failed_total
            # write — consumers unpack 2-tuples (app/run.py health
            # sampler); the reason is implied by the step
            self.failed_total[(duty.type, Step.CHAIN_INCLUSION)] += 1

    # -- analysis at duty expiry (ref: tracker.go:147-163) ----------------

    def _prereq_failed(self, prereq: Duty) -> bool:
        """Whether a prerequisite duty failed, robust to expiry ORDER:
        duties in a slot share one deadline and the proposer can expire
        before its randao — so when the prerequisite hasn't been analysed
        yet, judge its LIVE event set (events are final by now: both
        duties' deadlines have passed)."""
        if prereq in self._completed:
            return False
        if prereq in self._failed_steps:
            return True
        steps = self._steps.get(prereq)
        terminal = _TERMINAL_STEPS.get(prereq.type, Step.BCAST)
        if steps is not None:
            return terminal not in steps
        # no events at all: the prerequisite never even started — that IS
        # a prerequisite failure (ref: dutyFailedStep(empty) == failed)
        return True

    async def duty_expired(self, duty: Duty) -> DutyReport:
        steps = self._steps.pop(duty, set())
        parsigs = self._parsigs.pop(duty, {})
        expected = self._expected.pop(duty, set())
        errors = self._errors.pop(duty, [])
        terminal = _TERMINAL_STEPS.get(duty.type, Step.BCAST)
        success = terminal in steps

        # parsig consistency: more than one message root for one pubkey
        # (ref: parsigsByMsg.MsgRootsConsistent)
        inconsistent = [
            pk for pk, roots in parsigs.items() if len(roots) > 1
        ]
        if inconsistent:
            self.inconsistent_total[duty.type] += 1

        # participation + unexpected peers (ref: analyseParticipation):
        # dedup by (pubkey, share); a partial for a pubkey with no
        # scheduled definition is unexpected rather than participation
        counts: dict[int, int] = defaultdict(int)
        unexpected: dict[int, int] = defaultdict(int)
        check_unexpected = (
            duty.type not in _UNSCHEDULED_TYPES and expected
        )
        for pk, roots in parsigs.items():
            shares = set().union(*roots.values())
            if check_unexpected and pk is not None and pk not in expected:
                for idx in shares:
                    unexpected[idx] += 1
                    self.unexpected_total[idx] += 1
                continue
            for idx in shares:
                counts[idx] += 1
        participation = set(counts)

        failed_step = None
        reason = None
        if not success:
            # first pipeline step (up to this duty type's terminal step)
            # that never happened
            for step in Step:
                if step > terminal:
                    break
                if step not in steps:
                    failed_step = step
                    reason = _FAIL_REASONS.get(step, Reason.UNKNOWN)
                    break
            # refinement: threshold/aggregation failures with
            # inconsistent partials are a distinct (bug-class) reason —
            # except sync-committee duties where disagreement is expected
            if (
                failed_step
                in (Step.PARSIG_DB_THRESHOLD, Step.SIG_AGG)
                and inconsistent
            ):
                reason = (
                    Reason.PARSIG_INCONSISTENT_SYNC
                    if duty.type in _EXPECT_INCONSISTENT
                    else Reason.PARSIG_INCONSISTENT
                )
            # refinement: an error recorded at the fetch step is the
            # beacon node failing us (infrastructure), a silent stall is
            # the bug-class reason (ref: analyseFetcherFailed)
            if failed_step == Step.FETCHER and any(
                e.startswith(str(Step.FETCHER)) for e in errors
            ):
                reason = Reason.FETCH_BN_ERROR
            # refinement: a fetch-stage failure of a dependent duty is
            # attributed to its failed prerequisite (randao -> proposer);
            # takes precedence over the BN-error classification, matching
            # ref analyseFetcherFailedProposer
            if failed_step == Step.FETCHER and duty.type in _FETCH_PREREQ:
                prereq_type, prereq_reason = _FETCH_PREREQ[duty.type]
                if self._prereq_failed(Duty(duty.slot, prereq_type)):
                    reason = prereq_reason
            self.failed_total[(duty.type, failed_step)] += 1
            self._failed_steps[duty] = failed_step
            # bounded memory: only same-slot prerequisites consult this
            if len(self._failed_steps) > 1024:
                for k in list(self._failed_steps)[:512]:
                    self._failed_steps.pop(k, None)
        elif duty.type in _PREREQ_TYPES:
            self._completed[duty] = None
            # FIFO eviction, mirroring _failed_steps above
            if len(self._completed) > 1024:
                for k in list(self._completed)[:512]:
                    self._completed.pop(k, None)

        part_map = {
            idx: idx in participation for idx in self.peer_share_indices
        }
        for idx in participation:
            self.participation_total[idx] += 1

        # per-validator attribution: once the signing phase started
        # (duty data stored), every expected pubkey should assemble a
        # threshold of partials — those that did not are reported
        # individually, including under a duty-level success (partial
        # success: some validators signed, this one did not)
        pubkey_failures: dict[PubKey, Reason] = {}
        if expected and Step.DUTY_DB in steps:
            for pk in expected:
                roots = parsigs.get(pk)
                if not roots:
                    pubkey_failures[pk] = Reason.NO_LOCAL_PARTIAL
                    continue
                # aggregation needs a threshold of shares on ONE message
                # root — a union across conflicting roots can never
                # aggregate, so count per root
                best = max(len(s) for s in roots.values())
                total = len(set().union(*roots.values()))
                if best >= self.threshold:
                    continue
                if total >= self.threshold:
                    # enough shares overall but split across roots
                    pubkey_failures[pk] = (
                        Reason.PARSIG_INCONSISTENT_SYNC
                        if duty.type in _EXPECT_INCONSISTENT
                        else Reason.PARSIG_INCONSISTENT
                    )
                else:
                    pubkey_failures[pk] = Reason.INSUFFICIENT_PARTIALS
        if pubkey_failures:
            self.pubkey_failures_total[duty.type] += len(pubkey_failures)

        from charon_tpu.app.tracer import duty_trace_id  # lazy: core !-> app

        report = DutyReport(
            duty=duty,
            success=success,
            failed_step=failed_step,
            reason=reason,
            participation=part_map,
            errors=errors,
            participation_counts=dict(counts),
            expected_per_peer=len(expected),
            unexpected_shares=dict(unexpected),
            inconsistent_pubkeys=inconsistent,
            failed_pubkeys=pubkey_failures,
            trace_id=duty_trace_id(duty),
        )
        for sub in self._subs:
            res = sub(report)
            if hasattr(res, "__await__"):
                await res
        return report


def tracking(tracker: Tracker):
    """wire() option emitting tracker events around every edge
    (ref: core/tracking.go + core.WithTracking)."""

    def option(name: str, fn):
        steps = _EDGE_STEPS.get(name)
        if steps is None:
            return fn

        async def wrapped(duty, *args, **kwargs):
            try:
                result = await fn(duty, *args, **kwargs)
            except Exception as e:
                tracker.step_failed(duty, steps[-1], e)
                # The edge being INVOKED already proves its input-side
                # steps (e.g. a VC submitting partials proves
                # VALIDATOR_API even when the store's downstream fan-out
                # raises) — without this, one transient peer error
                # cascades back through the awaited chain and the
                # tracker misattributes the duty one step too early.
                for step in steps[:-1]:
                    tracker.step_event(duty, step)
                raise
            for step in steps:
                tracker.step_event(duty, step)
            if name == "fetcher.fetch" and args and hasattr(args[0], "keys"):
                tracker.duty_scheduled(duty, args[0].keys())
            if name in ("parsigdb.store_external", "parsigdb.store_internal") and args:
                for pubkey, psig in args[0].items():
                    tracker.partial_observed(
                        duty,
                        psig.share_idx,
                        pubkey=pubkey,
                        root=_parsig_root(psig),
                    )
            return result

        return wrapped

    return option
