"""Tracker: per-duty failure detection and peer participation.

Mirrors ref: core/tracker — every workflow component emits an event per
duty step (step enum tracker.go:20-34); when the Deadliner expires a duty
the tracker determines the first failing step and a reason
(tracker.go:103, reasons reason.go), plus per-peer participation from the
partial signatures observed (tracker.go:106) and unexpected-peer checks.

Wiring: `tracking(tracker)` is a wire() option that wraps every
subscription edge (ref: core/tracking.go wraps via core.WithTracking).
"""

from __future__ import annotations

import enum
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from charon_tpu.core.types import Duty, PubKey


class Step(enum.IntEnum):
    """Workflow steps in pipeline order (ref: core/tracker/tracker.go:20)."""

    SCHEDULER = 0
    FETCHER = 1
    CONSENSUS = 2
    DUTY_DB = 3
    VALIDATOR_API = 4
    PARSIG_DB_INTERNAL = 5
    PARSIG_EX = 6
    PARSIG_DB_THRESHOLD = 7
    SIG_AGG = 8
    AGG_SIG_DB = 9
    BCAST = 10

    def __str__(self) -> str:
        return self.name.lower()


# Map wire() edge names to the steps their completion proves. An edge
# firing proves the *previous* step delivered (e.g. fetcher.fetch being
# invoked proves the scheduler emitted the duty).
_EDGE_STEPS: dict[str, tuple[Step, ...]] = {
    "fetcher.fetch": (Step.SCHEDULER, Step.FETCHER),
    "consensus.propose": (Step.CONSENSUS,),
    "dutydb.store": (Step.DUTY_DB,),
    "parsigdb.store_internal": (Step.VALIDATOR_API, Step.PARSIG_DB_INTERNAL),
    "parsigex.broadcast": (Step.PARSIG_EX,),
    "parsigdb.store_external": (Step.PARSIG_EX,),
    "sigagg.aggregate": (Step.PARSIG_DB_THRESHOLD, Step.SIG_AGG),
    "aggsigdb.store": (Step.AGG_SIG_DB,),
    "broadcaster.broadcast": (Step.BCAST,),
}


class Reason(str, enum.Enum):
    """Failure reasons (ref: core/tracker/reason.go)."""

    NOT_SCHEDULED = "duty was never scheduled"
    FETCH_FAILED = "failed to fetch duty data from the beacon node"
    NO_CONSENSUS = "consensus was not reached"
    NO_LOCAL_PARTIAL = "validator client did not submit a partial signature"
    INSUFFICIENT_PARTIALS = "insufficient partial signatures from peers"
    AGGREGATION_FAILED = "threshold aggregation or verification failed"
    BROADCAST_FAILED = "failed to broadcast to the beacon node"
    UNKNOWN = "unexpected failure"


_FAIL_REASONS = {
    Step.SCHEDULER: Reason.NOT_SCHEDULED,
    Step.FETCHER: Reason.FETCH_FAILED,
    Step.CONSENSUS: Reason.NO_CONSENSUS,
    Step.DUTY_DB: Reason.NO_LOCAL_PARTIAL,
    Step.VALIDATOR_API: Reason.NO_LOCAL_PARTIAL,
    Step.PARSIG_DB_INTERNAL: Reason.INSUFFICIENT_PARTIALS,
    Step.PARSIG_EX: Reason.INSUFFICIENT_PARTIALS,
    Step.PARSIG_DB_THRESHOLD: Reason.AGGREGATION_FAILED,
    Step.SIG_AGG: Reason.AGGREGATION_FAILED,
    Step.AGG_SIG_DB: Reason.AGGREGATION_FAILED,
    Step.BCAST: Reason.BROADCAST_FAILED,
}


@dataclass
class DutyReport:
    duty: Duty
    success: bool
    failed_step: Step | None
    reason: Reason | None
    participation: dict[int, bool]  # share_idx -> partial sig seen
    errors: list[str] = field(default_factory=list)


ReportSub = Callable[[DutyReport], Awaitable[None] | None]


class Tracker:
    """threshold/peers: for participation accounting."""

    def __init__(self, peer_share_indices: list[int]) -> None:
        self.peer_share_indices = list(peer_share_indices)
        self._steps: dict[Duty, set[Step]] = defaultdict(set)
        self._participation: dict[Duty, set[int]] = defaultdict(set)
        self._errors: dict[Duty, list[str]] = defaultdict(list)
        self._subs: list[ReportSub] = []
        self.failed_total: dict[tuple, int] = defaultdict(int)
        self.success_total: dict[Duty, int] = {}
        self.participation_total: dict[int, int] = defaultdict(int)

    def subscribe(self, sub: ReportSub) -> None:
        self._subs.append(sub)

    # -- event intake -----------------------------------------------------

    def step_event(self, duty: Duty, step: Step) -> None:
        self._steps[duty].add(step)

    def step_failed(self, duty: Duty, step: Step, err: Exception) -> None:
        self._errors[duty].append(f"{step}: {err}")

    def partial_observed(self, duty: Duty, share_idx: int) -> None:
        self._participation[duty].add(share_idx)

    # -- analysis at duty expiry (ref: tracker.go:103) --------------------

    async def duty_expired(self, duty: Duty) -> DutyReport:
        steps = self._steps.pop(duty, set())
        participation = self._participation.pop(duty, set())
        errors = self._errors.pop(duty, [])
        success = Step.BCAST in steps

        failed_step = None
        reason = None
        if not success:
            # first pipeline step that never happened
            for step in Step:
                if step not in steps:
                    failed_step = step
                    reason = _FAIL_REASONS.get(step, Reason.UNKNOWN)
                    break
            self.failed_total[(duty.type, failed_step)] += 1

        part_map = {
            idx: idx in participation for idx in self.peer_share_indices
        }
        for idx in participation:
            self.participation_total[idx] += 1

        report = DutyReport(
            duty=duty,
            success=success,
            failed_step=failed_step,
            reason=reason,
            participation=part_map,
            errors=errors,
        )
        for sub in self._subs:
            res = sub(report)
            if hasattr(res, "__await__"):
                await res
        return report


def tracking(tracker: Tracker):
    """wire() option emitting tracker events around every edge
    (ref: core/tracking.go + core.WithTracking)."""

    def option(name: str, fn):
        steps = _EDGE_STEPS.get(name)
        if steps is None:
            return fn

        async def wrapped(duty, *args, **kwargs):
            try:
                result = await fn(duty, *args, **kwargs)
            except Exception as e:
                tracker.step_failed(duty, steps[-1], e)
                raise
            for step in steps:
                tracker.step_event(duty, step)
            if name in ("parsigdb.store_external", "parsigdb.store_internal") and args:
                for psig in args[0].values():
                    tracker.partial_observed(duty, psig.share_idx)
            return result

        return wrapped

    return option
