"""Eth2 duty data objects: unsigned inputs and signed outputs.

Mirrors the reference's UnsignedData / SignedData / Eth2SignedData value
taxonomy (ref: core/types.go:52-91, core/eth2signeddata.go,
core/unsigneddata.go, core/signeddata.go) with frozen dataclasses and
spec-exact SSZ roots (charon_tpu/eth2util/ssz.py).

Every signed object knows its signing domain and object root, so partial
signatures can be verified against pubshares at the API boundary
(ref: core/validatorapi/validatorapi.go:1213) and recovered group
signatures against the group key (ref: core/sigagg/sigagg.go:117) through
one generic path.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import ClassVar

from charon_tpu.eth2util import ssz
from charon_tpu.eth2util.signing import DomainName, ForkInfo

# ---------------------------------------------------------------------------
# Spec containers (subset needed by the duty workflow)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Checkpoint:
    epoch: int
    root: bytes  # 32

    ssz_fields: ClassVar = (ssz.UINT64, ssz.BYTES32)


@dataclass(frozen=True)
class AttestationData:
    slot: int
    index: int
    beacon_block_root: bytes
    source: Checkpoint
    target: Checkpoint

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.UINT64,
        ssz.BYTES32,
        ssz.Nested(),
        ssz.Nested(),
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class Attestation:
    aggregation_bits: tuple[bool, ...]
    data: AttestationData
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.Bitlist(2048),
        ssz.Nested(),
        ssz.BYTES96,
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class BeaconBlockHeader:
    slot: int
    proposer_index: int
    parent_root: bytes
    state_root: bytes
    body_root: bytes

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.UINT64,
        ssz.BYTES32,
        ssz.BYTES32,
        ssz.BYTES32,
    )

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class Proposal:
    """A block proposal: the spec header (whose root is signed) plus the
    opaque full/blinded body payload the beacon node gave us, round-tripped
    back on submission (the reference carries whole VersionedProposal
    objects, ref: core/unsigneddata.go VersionedProposal; the workflow only
    ever needs the root and the bytes)."""

    header: BeaconBlockHeader
    body: bytes = b""
    blinded: bool = False

    def hash_tree_root(self) -> bytes:
        return self.header.hash_tree_root()


@dataclass(frozen=True)
class AggregateAndProof:
    aggregator_index: int
    aggregate: Attestation
    selection_proof: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.UINT64, ssz.Nested(), ssz.BYTES96)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class SyncCommitteeMessage:
    slot: int
    beacon_block_root: bytes
    validator_index: int
    signature: bytes = bytes(96)

    # Signing root is over the block root only (spec: sync committee
    # messages sign the beacon block root).


@dataclass(frozen=True)
class SyncCommitteeContribution:
    slot: int
    beacon_block_root: bytes
    subcommittee_index: int
    aggregation_bits: tuple[bool, ...] = ()
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.BYTES32,
        ssz.UINT64,
        ssz.Bitvector(128),
        ssz.BYTES96,
    )

    def hash_tree_root(self) -> bytes:
        bits = self.aggregation_bits or tuple([False] * 128)
        tmp = replace(self, aggregation_bits=bits)
        return ssz.hash_tree_root(tmp)


@dataclass(frozen=True)
class ContributionAndProof:
    aggregator_index: int
    contribution: SyncCommitteeContribution
    selection_proof: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.UINT64, ssz.Nested(), ssz.BYTES96)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


# Canonical builder-spec ValidatorRegistrationV1 lives in
# eth2util/registration.py (single SSZ schema — two definitions of the
# same consensus container can silently drift); re-exported here for the
# core workflow's convenience.
from charon_tpu.eth2util.registration import (  # noqa: E402
    ValidatorRegistration,
)


@dataclass(frozen=True)
class VoluntaryExit:
    epoch: int
    validator_index: int

    ssz_fields: ClassVar = (ssz.UINT64, ssz.UINT64)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


# ---------------------------------------------------------------------------
# Unsigned duty data (consensus payloads)
# ---------------------------------------------------------------------------

# UnsignedData is duck-typed: any frozen value with hash_tree_root().
# Per-duty unsigned payloads (ref: core/unsigneddata.go):
#   ATTESTER          -> AttestationDuty (att data + committee info)
#   PROPOSER          -> Proposal
#   AGGREGATOR        -> Attestation (the aggregate to sign over)
#   SYNC_CONTRIBUTION -> SyncCommitteeContribution


@dataclass(frozen=True)
class SyncMessageDuty:
    """Consensus payload for a sync-committee message: the agreed head
    block root every member signs."""

    beacon_block_root: bytes

    def hash_tree_root(self) -> bytes:
        return self.beacon_block_root


@dataclass(frozen=True)
class AttestationDuty:
    """Consensus payload for an attester duty: the agreed attestation data
    plus the validator's committee coordinates (the reference keeps these
    in its AttestationData wrapper, ref: core/unsigneddata.go:60-100)."""

    data: AttestationData
    committee_length: int
    committee_index: int  # position of the validator in the committee
    validator_committee_index: int

    def hash_tree_root(self) -> bytes:
        return self.data.hash_tree_root()


# ---------------------------------------------------------------------------
# Signed data: a generic envelope with a domain registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SignedData:
    """A signable duty output: payload + BLS signature.

    kind selects the signing domain and how the object root is derived
    (ref: core/eth2signeddata.go implements one Go type per kind; here one
    envelope + a registry keeps the wire/db layers fully generic)."""

    kind: str
    payload: object
    signature: bytes = b""

    def with_signature(self, sig: bytes) -> "SignedData":
        return replace(self, signature=sig)

    def signing_root(self, fork: ForkInfo, slot_epoch: int) -> bytes:
        spec = SIGNED_KINDS[self.kind]
        return fork.signing_root(spec.domain, spec.object_root(self.payload))


@dataclass(frozen=True)
class KindSpec:
    domain: DomainName
    object_root: object  # Callable[[payload], bytes]


def _epoch_root(epoch: int) -> bytes:
    return ssz.UINT64.hash_tree_root(epoch)


def _slot_root(slot: int) -> bytes:
    return ssz.UINT64.hash_tree_root(slot)


SIGNED_KINDS: dict[str, KindSpec] = {
    "attestation": KindSpec(
        DomainName.BEACON_ATTESTER, lambda att: att.data.hash_tree_root()
    ),
    "block": KindSpec(
        DomainName.BEACON_PROPOSER, lambda p: p.hash_tree_root()
    ),
    "randao": KindSpec(DomainName.RANDAO, _epoch_root),
    "selection_proof": KindSpec(DomainName.SELECTION_PROOF, _slot_root),
    "aggregate_and_proof": KindSpec(
        DomainName.AGGREGATE_AND_PROOF, lambda a: a.hash_tree_root()
    ),
    "sync_message": KindSpec(
        DomainName.SYNC_COMMITTEE, lambda m: m.beacon_block_root
    ),
    "sync_selection": KindSpec(
        DomainName.SYNC_COMMITTEE_SELECTION_PROOF,
        lambda d: ssz.Container((ssz.UINT64, ssz.UINT64)).hash_tree_root(
            (d.slot, d.subcommittee_index)
        ),
    ),
    "contribution_and_proof": KindSpec(
        DomainName.CONTRIBUTION_AND_PROOF, lambda c: c.hash_tree_root()
    ),
    "registration": KindSpec(
        DomainName.APPLICATION_BUILDER, lambda r: r.hash_tree_root()
    ),
    "exit": KindSpec(
        DomainName.VOLUNTARY_EXIT, lambda e: e.hash_tree_root()
    ),
}


@dataclass(frozen=True)
class SyncSelectionData:
    slot: int
    subcommittee_index: int


@dataclass(frozen=True)
class ParSignedData:
    """A partially signed duty output carrying its share index
    (ref: core/types.go ParSignedData)."""

    data: SignedData
    share_idx: int

    def message_root(self) -> bytes:
        """Root identifying *what* was signed — partials for the same duty
        group by this before threshold recombination
        (ref: core/parsigdb/memory.go:198 groups by message root)."""
        spec = SIGNED_KINDS[self.data.kind]
        return spec.object_root(self.data.payload)
