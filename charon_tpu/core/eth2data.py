"""Eth2 duty data objects: unsigned inputs and signed outputs.

Mirrors the reference's UnsignedData / SignedData / Eth2SignedData value
taxonomy (ref: core/types.go:52-91, core/eth2signeddata.go,
core/unsigneddata.go, core/signeddata.go) with frozen dataclasses and
spec-exact SSZ roots (charon_tpu/eth2util/ssz.py).

Every signed object knows its signing domain and object root, so partial
signatures can be verified against pubshares at the API boundary
(ref: core/validatorapi/validatorapi.go:1213) and recovered group
signatures against the group key (ref: core/sigagg/sigagg.go:117) through
one generic path.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import ClassVar

from charon_tpu.eth2util import ssz
from charon_tpu.eth2util.signing import DomainName, ForkInfo

# ---------------------------------------------------------------------------
# Spec containers — canonical definitions live in eth2util/spec.py (single
# SSZ schema per consensus container); re-exported here for the workflow.
# ---------------------------------------------------------------------------

from charon_tpu.eth2util.spec import (  # noqa: E402,F401
    Attestation,
    AttestationData,
    BeaconBlockHeader,
    Checkpoint,
    VoluntaryExit,
)
from charon_tpu.eth2util import spec as _spec  # noqa: E402


@dataclass(frozen=True)
class Proposal:
    """A fork-versioned block proposal: the FULL spec block container
    (or its blinded builder variant), exactly as the beacon node returned
    it and exactly as it is re-submitted once group-signed. The signed
    root is the block root, which by SSZ construction equals the
    header-with-body-root root (ref: core/unsigneddata.go
    VersionedProposal carries the same per-fork go-eth2-client block
    union; router.go:151-175 routes on the version discriminator).

    Deneb-onward full proposals also carry the sidecar blobs + KZG proofs
    through consensus so the winning node can publish complete block
    contents (they do not enter the signing root)."""

    version: str  # fork name: "capella" | "deneb"
    block: object  # eth2util/spec per-fork (Blinded)BeaconBlock container
    blinded: bool = False
    kzg_proofs: tuple = ()
    blobs: tuple = ()

    @property
    def slot(self) -> int:
        return self.block.slot

    @property
    def proposer_index(self) -> int:
        return self.block.proposer_index

    def header(self) -> BeaconBlockHeader:
        return self.block.header()

    def hash_tree_root(self) -> bytes:
        return self.block.hash_tree_root()


# Forks whose FULL proposals travel as block *contents* (block + blobs +
# proofs) on the produce/publish endpoints rather than a bare block.
FORKS_WITH_CONTENTS = frozenset({"deneb"})

_hex0x = _spec.hex0x
_unhex0x = _spec.unhex0x


def sniff_block_version(block_json: dict) -> str:
    """Fork of a bare block JSON object when no Eth-Consensus-Version
    header accompanied it: the body's field set discriminates."""
    body = block_json.get("body", {})
    return "deneb" if "blob_kzg_commitments" in body else "capella"


def proposal_data_json(p: Proposal) -> dict:
    """The produceBlockV3 `data` payload: bare (blinded) block JSON, or
    deneb-style block contents for full post-deneb proposals
    (ref: router.go:151 produceBlockV3 response shapes)."""
    bj = _spec.to_json(p.block)
    if p.blinded or p.version not in FORKS_WITH_CONTENTS:
        return bj
    return {
        "block": bj,
        "kzg_proofs": [_hex0x(x) for x in p.kzg_proofs],
        "blobs": [_hex0x(x) for x in p.blobs],
    }


def proposal_from_data_json(version: str, blinded: bool, data: dict) -> Proposal:
    cls = _spec.block_class(version, blinded)
    if blinded or version not in FORKS_WITH_CONTENTS:
        return Proposal(version, _spec.from_json(cls, data), blinded)
    return Proposal(
        version,
        _spec.from_json(cls, data["block"]),
        blinded,
        kzg_proofs=tuple(_unhex0x(x) for x in data.get("kzg_proofs", ())),
        blobs=tuple(_unhex0x(x) for x in data.get("blobs", ())),
    )


def signed_proposal_json(p: Proposal, signature: bytes) -> dict:
    """The publishBlock / publishBlindedBlock POST body: a
    SignedBeaconBlock (message+signature), wrapped as signed block
    contents for full post-deneb proposals (ref: router.go:157-175
    submitProposal / submitBlindedBlock)."""
    signed = {
        "message": _spec.to_json(p.block),
        "signature": _hex0x(signature),
    }
    if p.blinded or p.version not in FORKS_WITH_CONTENTS:
        return signed
    return {
        "signed_block": signed,
        "kzg_proofs": [_hex0x(x) for x in p.kzg_proofs],
        "blobs": [_hex0x(x) for x in p.blobs],
    }


def proposal_data_ssz(p: Proposal) -> bytes:
    """SSZ wire body for the produceBlockV3 `data` payload (served when
    the VC sends Accept: application/octet-stream — Lighthouse-style
    clients prefer SSZ for blocks)."""
    if p.blinded or p.version not in FORKS_WITH_CONTENTS:
        return ssz.serialize(p.block)
    return ssz.serialize(
        _spec.BlockContentsDeneb(p.block, p.kzg_proofs, p.blobs)
    )


def signed_proposal_ssz(p: Proposal, signature: bytes) -> bytes:
    """SSZ wire body for publishBlock/publishBlindedBlock."""
    full_cls, blind_cls = _spec.FORK_SIGNED_BLOCKS[p.version]
    if p.blinded:
        return ssz.serialize(blind_cls(p.block, signature))
    if p.version not in FORKS_WITH_CONTENTS:
        return ssz.serialize(full_cls(p.block, signature))
    return ssz.serialize(
        _spec.SignedBlockContentsDeneb(
            full_cls(p.block, signature), p.kzg_proofs, p.blobs
        )
    )


def signed_proposal_from_ssz(
    data: bytes, blinded: bool, version: str
) -> tuple[Proposal, bytes]:
    """Parse an SSZ publish POST body. Unlike JSON there is no field-set
    sniffing — the spec REQUIRES the Eth-Consensus-Version header on
    SSZ requests, so `version` is mandatory."""
    full_cls, blind_cls = _spec.FORK_SIGNED_BLOCKS[version]
    if blinded:
        s = ssz.deserialize(blind_cls, data)
        return Proposal(version, s.message, True), s.signature
    if version not in FORKS_WITH_CONTENTS:
        s = ssz.deserialize(full_cls, data)
        return Proposal(version, s.message, False), s.signature
    sc = ssz.deserialize(_spec.SignedBlockContentsDeneb, data)
    return (
        Proposal(
            version,
            sc.signed_block.message,
            False,
            kzg_proofs=tuple(sc.kzg_proofs),
            blobs=tuple(sc.blobs),
        ),
        sc.signed_block.signature,
    )


def signed_proposal_from_json(
    j: dict, blinded: bool, version: str | None = None
) -> tuple[Proposal, bytes]:
    """Parse a publish POST body. `version` comes from the
    Eth-Consensus-Version header when the VC sent one; otherwise the
    block JSON is sniffed."""
    if "signed_block" in j:  # deneb block contents
        inner = j["signed_block"]
        kzg = tuple(_unhex0x(x) for x in j.get("kzg_proofs", ()))
        blobs = tuple(_unhex0x(x) for x in j.get("blobs", ()))
    else:
        inner = j
        kzg, blobs = (), ()
    msg = inner["message"]
    ver = version or sniff_block_version(msg)
    block = _spec.from_json(_spec.block_class(ver, blinded), msg)
    return (
        Proposal(ver, block, blinded, kzg_proofs=kzg, blobs=blobs),
        _unhex0x(inner["signature"]),
    )


@dataclass(frozen=True)
class AggregateAndProof:
    aggregator_index: int
    aggregate: Attestation
    selection_proof: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.UINT64, ssz.Nested(), ssz.BYTES96)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


@dataclass(frozen=True)
class SyncCommitteeMessage:
    slot: int
    beacon_block_root: bytes
    validator_index: int
    signature: bytes = bytes(96)

    # Signing root is over the block root only (spec: sync committee
    # messages sign the beacon block root).


@dataclass(frozen=True)
class SyncCommitteeContribution:
    slot: int
    beacon_block_root: bytes
    subcommittee_index: int
    aggregation_bits: tuple[bool, ...] = ()
    signature: bytes = bytes(96)

    ssz_fields: ClassVar = (
        ssz.UINT64,
        ssz.BYTES32,
        ssz.UINT64,
        ssz.Bitvector(128),
        ssz.BYTES96,
    )

    def hash_tree_root(self) -> bytes:
        bits = self.aggregation_bits or tuple([False] * 128)
        tmp = replace(self, aggregation_bits=bits)
        return ssz.hash_tree_root(tmp)


@dataclass(frozen=True)
class ContributionAndProof:
    aggregator_index: int
    contribution: SyncCommitteeContribution
    selection_proof: bytes = bytes(96)

    ssz_fields: ClassVar = (ssz.UINT64, ssz.Nested(), ssz.BYTES96)

    def hash_tree_root(self) -> bytes:
        return ssz.hash_tree_root(self)


# Canonical builder-spec ValidatorRegistrationV1 lives in
# eth2util/registration.py (single SSZ schema — two definitions of the
# same consensus container can silently drift); re-exported here for the
# core workflow's convenience.
from charon_tpu.eth2util.registration import (  # noqa: E402
    ValidatorRegistration,
)


# ---------------------------------------------------------------------------
# Unsigned duty data (consensus payloads)
# ---------------------------------------------------------------------------

# UnsignedData is duck-typed: any frozen value with hash_tree_root().
# Per-duty unsigned payloads (ref: core/unsigneddata.go):
#   ATTESTER          -> AttestationDuty (att data + committee info)
#   PROPOSER          -> Proposal
#   AGGREGATOR        -> Attestation (the aggregate to sign over)
#   SYNC_CONTRIBUTION -> SyncCommitteeContribution


@dataclass(frozen=True)
class SyncMessageDuty:
    """Consensus payload for a sync-committee message: the agreed head
    block root every member signs."""

    beacon_block_root: bytes

    def hash_tree_root(self) -> bytes:
        return self.beacon_block_root


@dataclass(frozen=True)
class AttestationDuty:
    """Consensus payload for an attester duty: the agreed attestation data
    plus the validator's committee coordinates (the reference keeps these
    in its AttestationData wrapper, ref: core/unsigneddata.go:60-100)."""

    data: AttestationData
    committee_length: int
    committee_index: int  # position of the validator in the committee
    validator_committee_index: int

    def hash_tree_root(self) -> bytes:
        return self.data.hash_tree_root()


# ---------------------------------------------------------------------------
# Signed data: a generic envelope with a domain registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SignedData:
    """A signable duty output: payload + BLS signature.

    kind selects the signing domain and how the object root is derived
    (ref: core/eth2signeddata.go implements one Go type per kind; here one
    envelope + a registry keeps the wire/db layers fully generic)."""

    kind: str
    payload: object
    signature: bytes = b""

    def with_signature(self, sig: bytes) -> "SignedData":
        return replace(self, signature=sig)

    def signing_root(self, fork: ForkInfo, slot_epoch: int) -> bytes:
        spec = SIGNED_KINDS[self.kind]
        return fork.signing_root(spec.domain, spec.object_root(self.payload))


@dataclass(frozen=True)
class KindSpec:
    domain: DomainName
    object_root: object  # Callable[[payload], bytes]


def _epoch_root(epoch: int) -> bytes:
    return ssz.UINT64.hash_tree_root(epoch)


def _slot_root(slot: int) -> bytes:
    return ssz.UINT64.hash_tree_root(slot)


SIGNED_KINDS: dict[str, KindSpec] = {
    "attestation": KindSpec(
        DomainName.BEACON_ATTESTER, lambda att: att.data.hash_tree_root()
    ),
    "block": KindSpec(
        DomainName.BEACON_PROPOSER, lambda p: p.hash_tree_root()
    ),
    "randao": KindSpec(DomainName.RANDAO, _epoch_root),
    "selection_proof": KindSpec(DomainName.SELECTION_PROOF, _slot_root),
    "aggregate_and_proof": KindSpec(
        DomainName.AGGREGATE_AND_PROOF, lambda a: a.hash_tree_root()
    ),
    "sync_message": KindSpec(
        DomainName.SYNC_COMMITTEE, lambda m: m.beacon_block_root
    ),
    "sync_selection": KindSpec(
        DomainName.SYNC_COMMITTEE_SELECTION_PROOF,
        lambda d: ssz.Container((ssz.UINT64, ssz.UINT64)).hash_tree_root(
            (d.slot, d.subcommittee_index)
        ),
    ),
    "contribution_and_proof": KindSpec(
        DomainName.CONTRIBUTION_AND_PROOF, lambda c: c.hash_tree_root()
    ),
    "registration": KindSpec(
        DomainName.APPLICATION_BUILDER, lambda r: r.hash_tree_root()
    ),
    "exit": KindSpec(
        DomainName.VOLUNTARY_EXIT, lambda e: e.hash_tree_root()
    ),
}


@dataclass(frozen=True)
class SyncSelectionData:
    slot: int
    subcommittee_index: int


@dataclass(frozen=True)
class ParSignedData:
    """A partially signed duty output carrying its share index
    (ref: core/types.go ParSignedData)."""

    data: SignedData
    share_idx: int

    def message_root(self) -> bytes:
        """Root identifying *what* was signed — partials for the same duty
        group by this before threshold recombination
        (ref: core/parsigdb/memory.go:198 groups by message root).
        Cached: parsigdb grouping AND tracker consistency analysis hash
        the same object on the store hot path."""
        cached = getattr(self, "_root_cache", None)
        if cached is None:
            spec = SIGNED_KINDS[self.data.kind]
            cached = spec.object_root(self.data.payload)
            object.__setattr__(self, "_root_cache", cached)
        return cached
