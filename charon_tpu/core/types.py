"""Abstract core value types.

Mirrors ref: core/types.go — Duty (slot, type), the DutyType enum, PubKey,
and the per-duty set maps keyed by validator pubkey ("critical for clusters
with a large number of DVs", ref: docs/architecture.md:131-133). Sets here
are plain dicts of frozen values: immutability replaces the reference's
defensive Clone() discipline (ref: docs/architecture.md:202-205).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import NewType

# 0x-prefixed lowercase hex of a 48-byte compressed BLS public key — the
# group (distributed validator) key, used as the set key everywhere
# (ref: core/types.go PubKey).
PubKey = NewType("PubKey", str)


def pubkey_from_bytes(b: bytes) -> PubKey:
    if len(b) != 48:
        raise ValueError("pubkey must be 48 bytes")
    return PubKey("0x" + b.hex())


def pubkey_to_bytes(pk: PubKey) -> bytes:
    if not pk.startswith("0x") or len(pk) != 98:
        raise ValueError(f"malformed pubkey {pk!r}")
    return bytes.fromhex(pk[2:])


class DutyType(enum.IntEnum):
    """Duty types (ref: core/types.go:30-50 — 14 types incl. the
    deprecated builder proposer)."""

    UNKNOWN = 0
    PROPOSER = 1
    ATTESTER = 2
    SIGNATURE = 3  # generic one-off signature (exit shares, etc.)
    EXIT = 4
    BUILDER_PROPOSER = 5  # deprecated upstream; kept for enum parity
    BUILDER_REGISTRATION = 6
    RANDAO = 7
    PREPARE_AGGREGATOR = 8
    AGGREGATOR = 9
    SYNC_MESSAGE = 10
    PREPARE_SYNC_CONTRIBUTION = 11
    SYNC_CONTRIBUTION = 12
    INFO_SYNC = 13

    def __str__(self) -> str:  # log-friendly
        return self.name.lower()


# Duty types that are scheduled directly from beacon-node duty queries; the
# rest are derived steps (randao before proposer, prepare before
# aggregator...) — ref: core/scheduler resolves attester/proposer/sync.
SCHEDULED_TYPES = (
    DutyType.ATTESTER,
    DutyType.PROPOSER,
    DutyType.SYNC_MESSAGE,
)


@dataclass(frozen=True, order=True)
class Duty:
    """One cluster-level unit of work: all validators' duties of one type
    in one slot flow together (ref: core/types.go Duty)."""

    slot: int
    type: DutyType

    def __str__(self) -> str:
        return f"{self.slot}/{self.type}"


def randao_duty(slot: int) -> Duty:
    return Duty(slot, DutyType.RANDAO)


def attester_duty(slot: int) -> Duty:
    return Duty(slot, DutyType.ATTESTER)


def proposer_duty(slot: int) -> Duty:
    return Duty(slot, DutyType.PROPOSER)
