"""Duty deadline engine: expiry-driven retry windows and store trimming.

Mirrors ref: core/deadline.go — duties expire lateFactor (5) slots after
their start (min 30s), after which stores trim them and the tracker runs
its failure analysis. asyncio redesign: one task per Deadliner draining a
heap instead of the reference's channel loop.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from charon_tpu.core.types import Duty

# Duties expire this many slots after their start (ref: core/deadline.go:23
# lateFactor = 5), with a minimum window (ref: core/deadline.go:26).
LATE_FACTOR = 5
MIN_WINDOW_SECS = 30.0


@dataclass(frozen=True)
class SlotClock:
    """Maps slots to wall-clock times (genesis + slot duration)."""

    genesis_time: float
    slot_duration: float

    def slot_start(self, slot: int) -> float:
        return self.genesis_time + slot * self.slot_duration

    def slot_at(self, t: float) -> int:
        return max(0, int((t - self.genesis_time) // self.slot_duration))

    def duty_deadline(self, duty: Duty) -> float:
        window = max(LATE_FACTOR * self.slot_duration, MIN_WINDOW_SECS)
        return self.slot_start(duty.slot) + window


class Deadliner:
    """Expires duties at their deadline (ref: core/deadline.go:28-43).

    add(duty) registers interest; expired duties are delivered to the
    callback exactly once. Duties already past deadline are dropped
    immediately (add returns False), matching the reference semantics.
    """

    def __init__(
        self,
        clock: SlotClock,
        on_expired: Callable[[Duty], Awaitable[None] | None],
        # wall clock by design: duty expiry tracks the slot timeline,
        # which IS wall-clock (genesis arithmetic) — an operator clock
        # step SHOULD move expiries with the chain's real schedule
        now: Callable[[], float] = time.time,  # lint: allow(monotonic-clock)
    ) -> None:
        self._clock = clock
        self._cb = on_expired
        self._now = now
        self._heap: list[tuple[float, Duty]] = []
        self._pending: set[Duty] = set()
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None

    def add(self, duty: Duty) -> bool:
        deadline = self._clock.duty_deadline(duty)
        if deadline <= self._now():
            return False
        if duty in self._pending:
            return True
        self._pending.add(duty)
        heapq.heappush(self._heap, (deadline, duty))
        self._wake.set()
        return True

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="deadliner")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            self._wake.clear()
            if not self._heap:
                await self._wake.wait()
                continue
            deadline, duty = self._heap[0]
            delay = deadline - self._now()
            if delay > 0:
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=delay)
                    continue  # new earlier duty may have arrived
                except asyncio.TimeoutError:
                    pass
            heapq.heappop(self._heap)
            self._pending.discard(duty)
            res = self._cb(duty)
            if asyncio.iscoroutine(res):
                await res
