"""DutyDB: in-memory store of consensus-agreed unsigned duty data with a
blocking query API.

Mirrors ref: core/dutydb/memory.go — the validator client's queries block
until consensus resolves for the slot (memory.go:143,168,197,237), a
unique index per (slot, type, pubkey) detects conflicting values (slashing
protection), and PubKeyByAttestation maps attestation data back to the
validator. asyncio redesign: awaits are futures resolved on store instead
of the reference's query channels.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict

from charon_tpu.core.eth2data import AttestationDuty, Proposal
from charon_tpu.core.types import Duty, DutyType, PubKey


class ConflictError(Exception):
    """A second, different value was stored under the same unique key —
    a potential slashing hazard (ref: core/dutydb/memory.go conflicts)."""


class _AwaitMap:
    """Keyed futures: await_(key) blocks until resolve(key, value)."""

    def __init__(self) -> None:
        self._values: dict = {}
        self._waiters: dict[object, list[asyncio.Future]] = defaultdict(list)

    async def await_(self, key):
        if key in self._values:
            return self._values[key]
        fut = asyncio.get_running_loop().create_future()
        self._waiters[key].append(fut)
        return await fut

    def resolve(self, key, value) -> None:
        self._values[key] = value
        for fut in self._waiters.pop(key, []):
            if not fut.done():
                fut.set_result(value)

    def get(self, key):
        return self._values.get(key)

    def trim(self, keep) -> None:
        self._values = {k: v for k, v in self._values.items() if keep(k)}
        # waiters for trimmed keys stay pending until duty expiry cancels
        # the calling request (vapi requests carry their own timeouts).


class DutyDB:
    """Stores the cluster-agreed unsigned data per duty."""

    def __init__(self) -> None:
        self._att = _AwaitMap()  # (slot, pubkey) -> AttestationDuty
        self._proposal = _AwaitMap()  # (slot, pubkey) -> Proposal
        self._agg_att = _AwaitMap()  # (slot, att_data_root) -> Attestation
        self._contrib = _AwaitMap()  # (slot, subcommittee, root) -> Contribution
        self._sync_msg = _AwaitMap()  # (slot, pubkey) -> SyncMessageDuty
        self._att_by_root: dict[tuple[int, bytes], PubKey] = {}
        self._unique: dict[tuple, bytes] = {}

    # -- store (wired to consensus output) --------------------------------

    async def store(self, duty: Duty, unsigned_set: dict[PubKey, object]) -> None:
        """Store consensus output (ref: core/dutydb/memory.go:70 Store)."""
        if duty.type == DutyType.INFO_SYNC:
            # protocol-internal negotiation result, not VC duty data —
            # consumed by the Prioritiser's own decided-subscriber
            # (ref: infosync runs a dedicated consensus instance whose
            # output never reaches the dutydb)
            return
        for pubkey, unsigned in unsigned_set.items():
            self._check_unique(duty, pubkey, unsigned)
            if duty.type == DutyType.ATTESTER:
                assert isinstance(unsigned, AttestationDuty)
                self._att.resolve((duty.slot, pubkey), unsigned)
                root = unsigned.data.hash_tree_root()
                self._att_by_root[(duty.slot, root)] = pubkey
            elif duty.type == DutyType.PROPOSER:
                assert isinstance(unsigned, Proposal)
                self._proposal.resolve((duty.slot, pubkey), unsigned)
            elif duty.type == DutyType.AGGREGATOR:
                # unsigned is an AggregateAndProof; key by the aggregated
                # attestation's data root (ref: memory.go agg att keying)
                root = unsigned.aggregate.data.hash_tree_root()
                self._agg_att.resolve((duty.slot, root), unsigned)
            elif duty.type == DutyType.SYNC_MESSAGE:
                self._sync_msg.resolve((duty.slot, pubkey), unsigned)
            elif duty.type == DutyType.SYNC_CONTRIBUTION:
                key = (
                    duty.slot,
                    unsigned.subcommittee_index,
                    unsigned.beacon_block_root,
                )
                self._contrib.resolve(key, unsigned)
            else:
                raise ValueError(f"dutydb does not store {duty.type}")

    def _check_unique(self, duty: Duty, pubkey: PubKey, unsigned) -> None:
        key = (duty.slot, duty.type, pubkey)
        root = unsigned.hash_tree_root()
        prev = self._unique.get(key)
        if prev is not None and prev != root:
            raise ConflictError(f"conflicting unsigned data for {key}")
        self._unique[key] = root

    # -- blocking queries (vapi side) -------------------------------------

    async def await_attestation(self, slot: int, pubkey: PubKey) -> AttestationDuty:
        return await self._att.await_((slot, pubkey))

    async def await_proposal(self, slot: int, pubkey: PubKey) -> Proposal:
        return await self._proposal.await_((slot, pubkey))

    async def await_aggregated_attestation(self, slot: int, att_data_root: bytes):
        return await self._agg_att.await_((slot, att_data_root))

    async def await_sync_message(self, slot: int, pubkey: PubKey):
        return await self._sync_msg.await_((slot, pubkey))

    async def await_sync_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: bytes
    ):
        return await self._contrib.await_(
            (slot, subcommittee_index, beacon_block_root)
        )

    def pubkey_by_attestation(self, slot: int, att_data_root: bytes) -> PubKey | None:
        """Map a submitted attestation back to its validator
        (ref: core/dutydb/memory.go:266)."""
        return self._att_by_root.get((slot, att_data_root))

    # -- trimming (wired to the Deadliner) --------------------------------

    def trim(self, expired: Duty) -> None:
        slot = expired.slot
        self._att.trim(lambda k: k[0] != slot)
        self._sync_msg.trim(lambda k: k[0] != slot)
        self._proposal.trim(lambda k: k[0] != slot)
        self._agg_att.trim(lambda k: k[0] != slot)
        self._contrib.trim(lambda k: k[0] != slot)
        self._att_by_root = {
            k: v for k, v in self._att_by_root.items() if k[0] != slot
        }
        self._unique = {
            k: v for k, v in self._unique.items() if k[0] != slot
        }
