"""Consensus controller: pluggable protocols behind one interface.

Mirrors ref: core/consensus/controller.go — a controller holds the default
protocol (QBFT) plus dynamically selected alternates (switched by the
priority protocol, ref app/app.go:650-668). Until the QBFT engine lands,
EchoConsensus provides the "fetch-leader echo" protocol used by the
single-process simnet (SURVEY.md §7 minimum slice): every node's fetcher
output is delivered straight to its subscribers, which is sound when all
nodes fetch identical data from a shared deterministic beacon mock.
"""

from __future__ import annotations

from typing import Awaitable, Callable

from charon_tpu.core.types import Duty, PubKey

DecidedSub = Callable[[Duty, dict[PubKey, object]], Awaitable[None]]


class EchoConsensus:
    """Trivial agreement for deterministic single-process clusters."""

    protocol_id = "echo/1.0.0"

    def __init__(self) -> None:
        self._subs: list[DecidedSub] = []
        self._decided: set[Duty] = set()

    def subscribe(self, sub: DecidedSub) -> None:
        self._subs.append(sub)

    async def propose(self, duty: Duty, unsigned_set: dict[PubKey, object]) -> None:
        if duty in self._decided:
            return
        self._decided.add(duty)
        for sub in self._subs:
            await sub(duty, unsigned_set)

    async def participate(self, duty: Duty) -> None:
        return None


class ConsensusController:
    """Holds default + current protocol (ref: controller.go:121)."""

    def __init__(self, default) -> None:
        self._default = default
        self._current = default
        self._protocols = {default.protocol_id: default}

    def register(self, consensus) -> None:
        self._protocols[consensus.protocol_id] = consensus

    def default_consensus(self):
        return self._default

    def current_consensus(self):
        return self._current

    def registered(self):
        """Registered protocols, current first — the preference order
        this node advertises in priority negotiation (ref: app/app.go
        Protocols ordering)."""
        return [self._current] + [
            p for p in self._protocols.values() if p is not self._current
        ]

    def set_current_for_protocol(self, protocol_id: str) -> bool:
        """Switch protocols by cluster preference (ref: app/app.go:650-668
        priority-driven switching)."""
        impl = self._protocols.get(protocol_id)
        if impl is None:
            return False
        self._current = impl
        return True

    # controller facade passes through to the current protocol
    def subscribe(self, sub) -> None:
        for impl in self._protocols.values():
            impl.subscribe(sub)

    async def propose(self, duty, unsigned_set) -> None:
        await self._current.propose(duty, unsigned_set)

    async def participate(self, duty) -> None:
        await self._current.participate(duty)
