"""Fetcher: stateless duty input data fetch + consensus proposal.

Mirrors ref: core/fetcher/fetcher.go — fetches attestation data / block
proposals / aggregates from the beacon node per duty (fetcher.go:114, 237),
pulling prerequisite aggregated signatures (randao for proposals, selection
proofs for aggregates) from AggSigDB, then proposes the unsigned data set
to consensus.
"""

from __future__ import annotations

from typing import Awaitable, Callable

from charon_tpu.core.eth2data import AttestationDuty, Proposal
from charon_tpu.core.scheduler import DutyDefinition
from charon_tpu.core.types import Duty, DutyType, PubKey


class Fetcher:
    def __init__(self, beacon) -> None:
        self.beacon = beacon
        self._propose = None
        self._await_agg_sig = None
        self._await_attestation = None

    def register_consensus(self, propose) -> None:
        self._propose = propose

    def register_agg_sig_db(self, await_) -> None:
        """ref: core/fetcher/fetcher.go:103 RegisterAggSigDB."""
        self._await_agg_sig = await_

    def register_await_attestation(self, await_att) -> None:
        """ref: core/fetcher/fetcher.go:109 RegisterAwaitAttData."""
        self._await_attestation = await_att

    async def fetch(
        self, duty: Duty, defs: dict[PubKey, DutyDefinition]
    ) -> None:
        """ref: core/fetcher/fetcher.go:50 Fetch."""
        if duty.type == DutyType.ATTESTER:
            unsigned = await self._fetch_attester(duty, defs)
        elif duty.type == DutyType.PROPOSER:
            unsigned = await self._fetch_proposer(duty, defs)
        elif duty.type == DutyType.AGGREGATOR:
            unsigned = await self._fetch_aggregator(duty, defs)
        elif duty.type == DutyType.SYNC_MESSAGE:
            unsigned = await self._fetch_sync_message(duty, defs)
        elif duty.type == DutyType.SYNC_CONTRIBUTION:
            unsigned = await self._fetch_sync_contribution(duty, defs)
        else:
            raise ValueError(f"unsupported fetch duty type {duty.type}")
        if unsigned:
            await self._propose(duty, unsigned)

    async def _fetch_attester(self, duty, defs):
        out: dict[PubKey, AttestationDuty] = {}
        # One att-data query per distinct committee (ref: fetcher.go:114).
        data_by_committee: dict[int, object] = {}
        for pubkey, d in defs.items():
            data = data_by_committee.get(d.committee_index)
            if data is None:
                data = await self.beacon.attestation_data(
                    duty.slot, d.committee_index
                )
                data_by_committee[d.committee_index] = data
            out[pubkey] = AttestationDuty(
                data=data,
                committee_length=d.committee_length,
                committee_index=d.committee_index,
                validator_committee_index=d.validator_committee_index,
            )
        return out

    async def _fetch_aggregator(self, duty, defs):
        """Aggregate attestations: needs the attestation data root from
        DutyDB plus the aggregated selection proof from AggSigDB
        (ref: core/fetcher/fetcher.go:158 aggregate flow)."""
        from charon_tpu.core.eth2data import AggregateAndProof

        out = {}
        for pubkey, d in defs.items():
            # the aggregated selection proof gates aggregation and is
            # embedded in the unsigned AggregateAndProof the VC signs
            # (ref: fetcher.go:158 + eth2exp selections).
            sel = await self._await_agg_sig(
                Duty(duty.slot, DutyType.PREPARE_AGGREGATOR), pubkey
            )
            att_duty = await self._await_attestation(duty.slot, pubkey)
            root = att_duty.data.hash_tree_root()
            agg_att = await self.beacon.aggregate_attestation(duty.slot, root)
            out[pubkey] = AggregateAndProof(
                aggregator_index=d.validator_index,
                aggregate=agg_att,
                selection_proof=sel.signature,
            )
        return out

    async def _fetch_sync_message(self, duty, defs):
        from charon_tpu.core.eth2data import SyncMessageDuty

        root = await self.beacon.sync_committee_block_root(duty.slot)
        return {pk: SyncMessageDuty(beacon_block_root=root) for pk in defs}

    async def _fetch_sync_contribution(self, duty, defs):
        out = {}
        for pubkey, d in defs.items():
            await self._await_agg_sig(
                Duty(duty.slot, DutyType.PREPARE_SYNC_CONTRIBUTION), pubkey
            )
            root = await self.beacon.sync_committee_block_root(duty.slot)
            out[pubkey] = await self.beacon.sync_contribution(
                duty.slot, d.committee_index, root
            )
        return out

    async def _fetch_proposer(self, duty, defs):
        out: dict[PubKey, Proposal] = {}
        for pubkey, d in defs.items():
            # The aggregated randao reveal gates the proposal fetch
            # (ref: fetcher.go:237-287 awaits DutyRandao aggregate).
            randao = await self._await_agg_sig(
                Duty(duty.slot, DutyType.RANDAO), pubkey
            )
            out[pubkey] = await self.beacon.block_proposal(
                duty.slot, d.validator_index, randao.signature
            )
        return out
