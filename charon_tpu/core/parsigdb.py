"""ParSigDB: partial-signature store with threshold grouping.

Mirrors ref: core/parsigdb/memory.go — keyed by (duty, pubkey): internal
stores fan out to the exchange component, incoming shares are deduped by
share index with conflict detection (memory.go:145-177), grouped by message
root, and exactly when the t-th matching signature arrives the batch is
emitted to the threshold subscribers (memory.go:198-225).

Batch-first addition: the store emits *duty-level* threshold batches — all
pubkeys of a duty that crossed the threshold in this store call are
delivered together, so sigagg can recombine them in one device program.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Awaitable, Callable

from charon_tpu.core.eth2data import ParSignedData
from charon_tpu.core.types import Duty, PubKey


class SigConflictError(Exception):
    """Same share index submitted two different signatures for one duty —
    byzantine behaviour worth surfacing (ref: memory.go conflict errors)."""


InternalSub = Callable[[Duty, dict[PubKey, ParSignedData]], Awaitable[None]]
ThresholdSub = Callable[
    [Duty, dict[PubKey, list[ParSignedData]]], Awaitable[None]
]


class ParSigDB:
    def __init__(self, threshold: int) -> None:
        self.threshold = threshold
        # (duty, pubkey) -> share_idx -> ParSignedData
        self._store: dict[tuple[Duty, PubKey], dict[int, ParSignedData]] = (
            defaultdict(dict)
        )
        self._emitted: set[tuple[Duty, PubKey]] = set()
        self._internal_subs: list[InternalSub] = []
        self._threshold_subs: list[ThresholdSub] = []

    def subscribe_internal(self, sub: InternalSub) -> None:
        self._internal_subs.append(sub)

    def subscribe_threshold(self, sub: ThresholdSub) -> None:
        self._threshold_subs.append(sub)

    # -- stores -----------------------------------------------------------

    async def store_internal(
        self, duty: Duty, signed_set: dict[PubKey, ParSignedData]
    ) -> None:
        """Store our own partial signatures and fan them out to the peers
        via the subscribed exchange (ref: memory.go:57-77).

        Local store FIRST: the node's own partial must survive a failing
        peer exchange (it is one of the t the cluster needs), so the
        store cannot sit downstream of the network call. Exchange
        failures are isolated per sub — they are attributed at their own
        wire() edge and must not erase the completed local store."""
        await self.store_external(duty, signed_set)
        for sub in self._internal_subs:
            try:
                await sub(duty, signed_set)
            except Exception as e:  # noqa: BLE001 — exchange is best-effort
                from charon_tpu.app import log

                log.warn(
                    "partial-signature exchange failed",
                    topic="parsigdb",
                    duty=str(duty),
                    err=f"{type(e).__name__}: {e}",
                )

    async def store_external(
        self, duty: Duty, signed_set: dict[PubKey, ParSignedData]
    ) -> None:
        """Store peer (or local) partials; emit one duty-level batch for
        every pubkey that reached the threshold in this call."""
        ready: dict[PubKey, list[ParSignedData]] = {}
        for pubkey, psig in signed_set.items():
            batch = self._put(duty, pubkey, psig)
            if batch is not None:
                ready[pubkey] = batch
        if ready:
            for sub in self._threshold_subs:
                # isolate: this store may be running inside a PEER's
                # send chain (mem transport); a local aggregation
                # failure is attributed at its own wire() edge and must
                # not cascade back into the sender's pipeline
                try:
                    await sub(duty, ready)
                except Exception as e:  # noqa: BLE001
                    from charon_tpu.app import log

                    log.warn(
                        "threshold subscriber failed",
                        topic="parsigdb",
                        duty=str(duty),
                        err=f"{type(e).__name__}: {e}",
                    )

    def _put(
        self, duty: Duty, pubkey: PubKey, psig: ParSignedData
    ) -> list[ParSignedData] | None:
        key = (duty, pubkey)
        sigs = self._store[key]
        prev = sigs.get(psig.share_idx)
        if prev is not None:
            if prev.data.signature != psig.data.signature:
                raise SigConflictError(
                    f"share {psig.share_idx} equivocated for {duty}/{pubkey}"
                )
            return None  # duplicate
        sigs[psig.share_idx] = psig

        if key in self._emitted:
            return None
        # Group by message root; emit exactly when some root hits t
        # (ref: memory.go:198-225 emits when len == threshold).
        by_root: dict[bytes, list[ParSignedData]] = defaultdict(list)
        for s in sigs.values():
            by_root[s.message_root()].append(s)
        batch = by_root.get(psig.message_root())
        if batch is not None and len(batch) == self.threshold:
            self._emitted.add(key)
            return sorted(batch, key=lambda s: s.share_idx)
        return None

    # -- trimming ---------------------------------------------------------

    def trim(self, expired: Duty) -> None:
        self._store = defaultdict(
            dict,
            {k: v for k, v in self._store.items() if k[0] != expired},
        )
        self._emitted = {k for k in self._emitted if k[0] != expired}
