"""ParSigDB: partial-signature store with threshold grouping.

Mirrors ref: core/parsigdb/memory.go — keyed by (duty, pubkey): internal
stores fan out to the exchange component, incoming shares are deduped by
share index with conflict detection (memory.go:145-177), grouped by message
root, and exactly when the t-th matching signature arrives the batch is
emitted to the threshold subscribers (memory.go:198-225).

Batch-first addition: the store emits *duty-level* threshold batches — all
pubkeys of a duty that crossed the threshold in this store call are
delivered together, so sigagg can recombine them in one device program.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Awaitable, Callable

from charon_tpu.core.eth2data import ParSignedData
from charon_tpu.core.types import Duty, PubKey


class SigConflictError(Exception):
    """Same share index submitted two different signatures for one duty —
    byzantine behaviour worth surfacing (ref: memory.go conflict errors).

    Since ISSUE 16 the store no longer raises this: a raise mid-batch
    aborted the remaining (honest) pubkeys of the same store call, so one
    double-signed lane could take down a whole peer set. Conflicts are now
    recorded as evidence (first signature wins, `conflicts` counter +
    EvidenceRegistry), and the class stays importable for callers that
    still reference it."""


InternalSub = Callable[[Duty, dict[PubKey, ParSignedData]], Awaitable[None]]
ThresholdSub = Callable[
    [Duty, dict[PubKey, list[ParSignedData]]], Awaitable[None]
]


class ParSigDB:
    def __init__(
        self,
        threshold: int,
        evidence=None,  # core/evidence.EvidenceRegistry; None = unrecorded
        max_pending_per_peer: int = 512,
    ) -> None:
        self.threshold = threshold
        self.evidence = evidence
        # Cap on distinct un-emitted (duty, pubkey) keys ONE share index
        # may hold partials for: without it a byzantine peer streaming
        # valid-format partials for fabricated keys grows the store
        # without limit between trims. Honest peers hold at most
        # (live duties x validators) pending keys at once.
        self.max_pending_per_peer = max_pending_per_peer
        self.conflicts = 0  # double-signed lanes (first wins)
        self.flood_dropped = 0  # partials refused at the pending cap
        # (duty, pubkey) -> share_idx -> ParSignedData
        self._store: dict[tuple[Duty, PubKey], dict[int, ParSignedData]] = (
            defaultdict(dict)
        )
        self._pending_per_peer: dict[int, set[tuple[Duty, PubKey]]] = (
            defaultdict(set)
        )
        self._emitted: set[tuple[Duty, PubKey]] = set()
        self._internal_subs: list[InternalSub] = []
        self._threshold_subs: list[ThresholdSub] = []

    def subscribe_internal(self, sub: InternalSub) -> None:
        self._internal_subs.append(sub)

    def subscribe_threshold(self, sub: ThresholdSub) -> None:
        self._threshold_subs.append(sub)

    # -- stores -----------------------------------------------------------

    async def store_internal(
        self, duty: Duty, signed_set: dict[PubKey, ParSignedData]
    ) -> None:
        """Store our own partial signatures and fan them out to the peers
        via the subscribed exchange (ref: memory.go:57-77).

        Local store FIRST: the node's own partial must survive a failing
        peer exchange (it is one of the t the cluster needs), so the
        store cannot sit downstream of the network call. Exchange
        failures are isolated per sub — they are attributed at their own
        wire() edge and must not erase the completed local store."""
        await self.store_external(duty, signed_set)
        for sub in self._internal_subs:
            try:
                await sub(duty, signed_set)
            except Exception as e:  # noqa: BLE001 — exchange is best-effort
                from charon_tpu.app import log

                log.warn(
                    "partial-signature exchange failed",
                    topic="parsigdb",
                    duty=str(duty),
                    err=f"{type(e).__name__}: {e}",
                )

    async def store_external(
        self, duty: Duty, signed_set: dict[PubKey, ParSignedData]
    ) -> None:
        """Store peer (or local) partials; emit one duty-level batch for
        every pubkey that reached the threshold in this call."""
        ready: dict[PubKey, list[ParSignedData]] = {}
        for pubkey, psig in signed_set.items():
            batch = self._put(duty, pubkey, psig)
            if batch is not None:
                ready[pubkey] = batch
        if ready:
            for sub in self._threshold_subs:
                # isolate: this store may be running inside a PEER's
                # send chain (mem transport); a local aggregation
                # failure is attributed at its own wire() edge and must
                # not cascade back into the sender's pipeline
                try:
                    await sub(duty, ready)
                except Exception as e:  # noqa: BLE001
                    from charon_tpu.app import log

                    log.warn(
                        "threshold subscriber failed",
                        topic="parsigdb",
                        duty=str(duty),
                        err=f"{type(e).__name__}: {e}",
                    )

    def _put(
        self, duty: Duty, pubkey: PubKey, psig: ParSignedData
    ) -> list[ParSignedData] | None:
        key = (duty, pubkey)
        sigs = self._store[key]
        prev = sigs.get(psig.share_idx)
        if prev is not None:
            if prev.data.signature != psig.data.signature:
                # Byzantine double-sign: the share equivocated for this
                # duty/validator. First signature wins; record evidence
                # and CONTINUE — raising here would let one adversarial
                # lane abort the remaining honest pubkeys of the batch.
                self.conflicts += 1
                if self.evidence is not None:
                    self.evidence.record(
                        psig.share_idx,
                        "parsig_conflict",
                        detail=f"{duty}/{pubkey}",
                    )
            return None  # duplicate or conflicting (first wins)
        pending = self._pending_per_peer[psig.share_idx]
        if key not in self._emitted and key not in pending:
            if len(pending) >= self.max_pending_per_peer:
                self.flood_dropped += 1
                if self.evidence is not None:
                    self.evidence.record(
                        psig.share_idx, "parsig_flood"
                    )
                return None
            pending.add(key)
        sigs[psig.share_idx] = psig

        if key in self._emitted:
            return None
        # Group by message root; emit exactly when some root hits t
        # (ref: memory.go:198-225 emits when len == threshold).
        by_root: dict[bytes, list[ParSignedData]] = defaultdict(list)
        for s in sigs.values():
            by_root[s.message_root()].append(s)
        batch = by_root.get(psig.message_root())
        if batch is not None and len(batch) == self.threshold:
            self._emitted.add(key)
            # emitted keys stop counting against every contributor's
            # pending budget
            for peer_pending in self._pending_per_peer.values():
                peer_pending.discard(key)
            return sorted(batch, key=lambda s: s.share_idx)
        return None

    # -- trimming ---------------------------------------------------------

    def trim(self, expired: Duty) -> None:
        self._store = defaultdict(
            dict,
            {k: v for k, v in self._store.items() if k[0] != expired},
        )
        self._emitted = {k for k in self._emitted if k[0] != expired}
        for pending in self._pending_per_peer.values():
            for key in [k for k in pending if k[0] == expired]:
                pending.discard(key)
