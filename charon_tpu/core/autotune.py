"""Startup kernel auto-tuner + ahead-of-time compile-artifact cache.

ROADMAP item 3: three device-side multipliers (Pippenger/Straus MSM,
int8-MXU Montgomery, fused-Fp2 Pallas) are built and validated but were
hand-toggled per deployment via env vars. This module makes kernel
choice SELF-TUNING and cold start ARTIFACT-CACHED:

  * `KernelConfig` — the one typed source of truth for kernel routing.
    `apply()` pushes it into the trace-time dispatch flags
    (`ops/msm.set_msm`, `ops/limb.set_mxu`/`set_pallas`,
    `ops/fptower.set_fp2_fusion`) and drops the jitted-kernel caches so
    the flip actually takes effect. The legacy `CHARON_MSM` /
    `CHARON_MXU_MONT` env toggles are folded in as explicit overrides
    (`env_overrides`) that outrank the tuned profile — the ops/ hot
    paths no longer read the environment.

  * `resolve()` — the startup tuner. It walks
    `core/cryptoplane.kernel_inventory()` (the PR 11 registry of engine
    families + mesh program variants), micro-benches each CANDIDATE
    axis on canonical bucket-ladder shapes for the detected platform,
    and persists the winning profile (JSON, schema-versioned, keyed by
    platform + jax version + the same `ops/*.py` + `parallel/mesh.py`
    source digest the blessed kernel manifest uses —
    `analysis/jaxpr_check.source_digest`, reused, not duplicated) next
    to the jit cache managed by `jaxcache.py`. A second boot loads the
    profile, SKIPS the micro-bench, and dispatches warm; a stale digest
    (kernel sources actually changed) falls back to re-tune.

  * `aot_prewarm()` — the compile-artifact story. After tuning, the
    chosen variants are lowered + compiled for the prewarm shape ladder
    so the persistent compilation cache absorbs the binaries; the next
    boot replays those compiles as cache loads (seconds, not the 327 s
    XLA:CPU measured cold for one h2c program — PERF.md).

Failure policy (app/run.py wiring): tuning failures degrade to
`KernelConfig()` defaults and never block boot. Hosts without jax skip
loudly in `auto` mode and raise `PlaneConfigError` in `on`/`force`
(asking for a device tune without a device stack is a deploy mistake).
All timing in this module uses the monotonic clocks (core/ invariant).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable

from charon_tpu.app import log

# Canonical micro-bench / prewarm shapes: the blsops bucket ladder the
# coalescer pads to (4-lane floor; parallel/mesh.py prewarms the same
# 256-lane burst ceiling).
TUNE_LANES = 8
TUNE_REPS = 3
PREWARM_LANES = (4, 16, 64, 256)

PROFILE_VERSION = 1
PROFILE_BASENAME = "autotune_profile.json"
# written by mark_prewarmed() after a SUCCESSFUL crypto-plane prewarm;
# warm_boot_ready requires it (a warm micro-bench cache alone does not
# make the duty pairing programs cheap)
PREWARM_MARKER_BASENAME = "prewarm_complete.json"
# Append-only field ledger (mirrors analysis/schema_check.py): existing
# fields never move or vanish, new fields append, and a NEW field may
# only join PROFILE_REQUIRED together with a version bump. The blessed
# snapshot lives in tests/testdata/autotune_schema.json and
# tests/test_autotune.py gates the contract with a seeded-violation
# battery.
PROFILE_FIELDS = (
    "version",
    "platform",
    "jax_version",
    "source_digest",
    "host",
    "config",
    "sources",
    "timings",
    "families",
    "tune_lanes",
    "prewarm_lanes",
)
PROFILE_REQUIRED = (
    "version",
    "platform",
    "jax_version",
    "source_digest",
    "config",
)

# Legacy env toggles, folded in as explicit KernelConfig overrides
# (deploy-pinned; they outrank the tuned profile). Kept for the dryrun
# env contract (CI.md pins CHARON_MSM=0 + CHARON_MXU_MONT=0) and live
# fleet rollbacks; new deployments should pin via --crypto-autotune.
_ENV_TOGGLES = (
    ("CHARON_MSM", "msm", lambda v: v != "0"),
    ("CHARON_MXU_MONT", "mxu_mont", lambda v: v == "1"),
)
_ENV_WARNED = False


class ProfileError(ValueError):
    """A kernel profile that cannot be used (typed-errors invariant:
    distinguishable from crypto/wire failures — the resolver degrades
    to defaults or re-tunes, never crashes the boot path on one).

    `reason` is one of: missing | unreadable | corrupt | schema |
    version.
    """

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


@dataclass(frozen=True)
class KernelConfig:
    """Typed kernel-routing choice — THE source of truth the tuner,
    the env overrides, and the CLI all resolve into.

    `pallas` keeps three-state semantics (None = auto: on for the
    uint32 geometry on a real TPU backend) because forcing it on a CPU
    host would route into Mosaic kernels that cannot lower there; the
    tuner treats it as a platform fact, not a tunable axis.
    """

    msm: bool = True  # Straus joint windowed mul in threshold recombine
    mxu_mont: bool = False  # int8-MXU Montgomery decomposition
    fp2_fusion: bool = True  # fused-Fp2 Pallas kernels (needs pallas)
    pallas: bool | None = None  # None = auto (TPU + uint32 geometry)
    ceremony_straus: bool = True  # Straus vs per-lane in commitment eval
    ceremony_msm_w8: bool = True  # Pippenger window 8 (else 4) in g1_msm

    # the axes resolve()/micro_bench() may tune (bool-valued)
    TUNABLE = (
        "msm",
        "mxu_mont",
        "fp2_fusion",
        "ceremony_straus",
        "ceremony_msm_w8",
    )

    def apply(self) -> bool:
        """Push this config into the trace-time dispatch flags and drop
        the jitted-kernel caches (the flip is trace-time routing — a
        cached executable would silently ignore it). Returns False on
        hosts without jax, where there are no device kernels to route.
        """
        try:
            from charon_tpu.ops import blsops, fptower, limb
            from charon_tpu.ops import msm as MSM
        except ImportError:
            return False
        MSM.set_msm(self.msm)
        MSM.set_ceremony_straus(self.ceremony_straus)
        MSM.set_ceremony_window(8 if self.ceremony_msm_w8 else 4)
        limb.set_mxu(self.mxu_mont)
        limb.set_pallas(self.pallas)
        fptower.set_fp2_fusion(self.fp2_fusion)
        blsops.clear_kernel_caches()
        return True

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class Candidate:
    """One tunable axis: how to decide whether it applies on this
    platform/geometry and how to micro-bench a value for it.

    `builder(lanes)` must return a zero-arg closure that runs ONE
    device dispatch of a kernel dominated by this axis (first call
    compiles; see docs/development.md "add a tuner candidate").
    """

    field: str
    doc: str
    applicable: Callable[[], bool]
    builder: Callable[[int], Callable[[], None]]
    values: tuple = (True, False)


@dataclass(frozen=True)
class TuneResult:
    """What `resolve()` decided and why — the run.py lifecycle hook
    logs it and app/metrics.autotune_hook turns the observer events
    into counters."""

    config: KernelConfig
    outcome: str  # hit | tuned | off | skipped
    applied: bool  # False only on hosts without jax
    bench_runs: int  # 0 on a pure profile load
    sources: dict  # axis -> profile|tuned|env|default|inapplicable
    timings: dict  # axis -> {"on"/"off": seconds}
    overrides: dict  # env-derived field overrides in force
    profile_path: str | None


def env_overrides(environ=None) -> dict:
    """Explicit KernelConfig overrides from the legacy env toggles.

    Deploy-pinned and therefore ranked ABOVE the tuned profile: an
    operator who exported CHARON_MSM=0 to dodge a compiler regression
    must not have the tuner silently re-enable the kernel.
    """
    env = os.environ if environ is None else environ
    out = {}
    for var, field, decode in _ENV_TOGGLES:
        if var in env:
            out[field] = decode(env[var])
    return out


def apply_env(environ=None) -> KernelConfig:
    """Defaults + env overrides, applied. The entry point for harnesses
    that pin kernels by env instead of running the tuner
    (__graft_entry__'s canonical dryrun env, .tpu_watch5.sh)."""
    cfg = dataclasses.replace(KernelConfig(), **env_overrides(environ))
    cfg.apply()
    return cfg


# ---------------------------------------------------------------------------
# Candidate axes + their micro-bench kernels
# ---------------------------------------------------------------------------


def _recombine_builder(lanes: int, t: int = 3) -> Callable[[], None]:
    """Threshold recombination burst — the kernel whose routing the msm
    axis decides (blsops.threshold_recombine: Straus joint windowed mul
    vs per-lane double-and-add)."""
    import jax
    import numpy as np

    from charon_tpu.crypto.g1g2 import G2_GEN
    from charon_tpu.ops import blsops, limb
    from charon_tpu.ops import curve as C

    ctx, fr_ctx = limb.default_fp_ctx(), limb.default_fr_ctx()
    n = blsops.bucket_lanes(lanes)
    sig = C.g2_pack(ctx, [G2_GEN] * (n * t))
    sig = jax.tree_util.tree_map(
        lambda a: a.reshape((n, t) + a.shape[1:]), sig
    )
    idx = np.tile(np.arange(1, t + 1, dtype=np.int32), (n, 1))
    fn = jax.jit(
        lambda s, i: blsops.threshold_recombine(ctx, fr_ctx, t, s, i)
    )

    def run() -> None:
        jax.block_until_ready(fn(sig, idx))

    return run


def _mont_mul_builder(lanes: int) -> Callable[[], None]:
    """Stacked base-field Montgomery multiply — the kernel the mxu_mont
    axis reroutes (XLA conv / Pallas VMEM / int8-MXU Toeplitz)."""
    import jax
    import jax.numpy as jnp

    from charon_tpu.ops import blsops, limb

    ctx = limb.default_fp_ctx()
    n = blsops.bucket_lanes(lanes)
    a = jnp.asarray(
        limb.ctx_pack(
            ctx, [(i * 2654435761 + 1) % ctx.modulus for i in range(n)]
        )
    )
    fn = jax.jit(lambda x, y: limb.mont_mul(ctx, x, y))

    def run() -> None:
        jax.block_until_ready(fn(a, a))

    return run


def _fp2_batch_builder(lanes: int) -> Callable[[], None]:
    """Batched Fp2 mul/sqr level — fused Pallas kernels vs the stacked
    XLA path (fptower.fp2_batch)."""
    import jax
    import jax.numpy as jnp

    from charon_tpu.ops import blsops, fptower, limb

    ctx = limb.default_fp_ctx()
    n = blsops.bucket_lanes(lanes)
    a = jnp.asarray(limb.ctx_pack(ctx, [i + 1 for i in range(n)]))

    def level(x):
        e = (x, x)
        return fptower.fp2_batch(
            ctx, [("mul", e, e), ("sqr", e), ("mul", e, e), ("sqr", e)]
        )

    fn = jax.jit(level)

    def run() -> None:
        jax.block_until_ready(fn(a))

    return run


def _ceremony_eval_builder(lanes: int, t: int = 3) -> Callable[[], None]:
    """DKG commitment-polynomial evaluation wave — the kernel the
    ceremony_straus axis routes (blsops._commitment_eval_kernel: Straus
    joint windowed mul vs per-lane double-and-add + fold)."""
    import jax
    import numpy as np

    from charon_tpu.crypto.g1g2 import G1_GEN
    from charon_tpu.ops import blsops, limb
    from charon_tpu.ops import curve as C

    ctx, fr_ctx = limb.default_fp_ctx(), limb.default_fr_ctx()
    n = blsops.bucket_lanes(lanes)
    commits = C.g1_pack(ctx, [G1_GEN] * (n * t))
    commits = jax.tree_util.tree_map(
        lambda a: a.reshape((n, t) + a.shape[1:]), commits
    )
    xs = np.arange(1, n + 1, dtype=np.int32)
    fn = blsops._commitment_eval_kernel(ctx, fr_ctx, 1, t, 32)

    def run() -> None:
        jax.block_until_ready(fn(commits, xs))

    return run


def _ceremony_msm_builder(lanes: int) -> Callable[[], None]:
    """Segmented G1 MSM burst — the kernel the ceremony_msm_w8 axis
    sizes (Pippenger bucket window 8 vs 4 in blsops._g1_msm_kernel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from charon_tpu.crypto.g1g2 import G1_GEN
    from charon_tpu.ops import blsops, limb
    from charon_tpu.ops import curve as C

    ctx, fr_ctx = limb.default_fp_ctx(), limb.default_fr_ctx()
    n = blsops.bucket_lanes(lanes)
    pts = C.g1_pack(ctx, [G1_GEN] * n)
    scalars = jnp.asarray(
        limb.ctx_pack(fr_ctx, [i + 1 for i in range(n)])
    )
    seg = jnp.asarray(np.zeros(n, dtype=np.int32))
    fn = blsops._g1_msm_kernel(ctx, fr_ctx, 1, 255)

    def run() -> None:
        jax.block_until_ready(fn(pts, scalars, seg))

    return run


def _always(_=None) -> bool:
    return True


def _mxu_applicable() -> bool:
    from charon_tpu.ops import limb

    # the int8-MXU decomposition only exists for the 12-bit geometry
    # (the CPU-fallback profile packs 24-bit limbs — bench.py guards
    # the same way)
    return limb.default_fp_ctx().limb_bits == 12


def _fp2_applicable() -> bool:
    from charon_tpu.ops import limb

    # fusion only reroutes anything when the Pallas rung is live
    return limb._pallas_active(limb.default_fp_ctx())


CANDIDATES: dict[str, Candidate] = {}


def register_candidate(cand: Candidate) -> None:
    """Register a tunable axis (idempotent by field name). New kernels
    register here instead of growing another env var — see
    docs/development.md."""
    if cand.field not in KernelConfig.TUNABLE:
        raise ValueError(
            f"candidate field {cand.field!r} is not a tunable "
            f"KernelConfig axis {KernelConfig.TUNABLE}"
        )
    CANDIDATES[cand.field] = cand


register_candidate(
    Candidate(
        field="msm",
        doc="Straus joint windowed mul vs per-lane double-and-add",
        applicable=_always,
        builder=_recombine_builder,
    )
)
register_candidate(
    Candidate(
        field="mxu_mont",
        doc="int8-MXU Montgomery decomposition vs Pallas/XLA mont_mul",
        applicable=_mxu_applicable,
        builder=_mont_mul_builder,
    )
)
register_candidate(
    Candidate(
        field="fp2_fusion",
        doc="fused-Fp2 Pallas kernels vs stacked-XLA fp2 level",
        applicable=_fp2_applicable,
        builder=_fp2_batch_builder,
    )
)
register_candidate(
    Candidate(
        field="ceremony_straus",
        doc="Straus joint mul vs per-lane in DKG commitment eval",
        applicable=_always,
        builder=_ceremony_eval_builder,
    )
)
register_candidate(
    Candidate(
        field="ceremony_msm_w8",
        doc="Pippenger window 8 vs 4 in ceremony segmented G1 MSM",
        applicable=_always,
        builder=_ceremony_msm_builder,
    )
)


def _label(value) -> str:
    if value is True:
        return "on"
    if value is False:
        return "off"
    return str(value)


def micro_bench(
    candidates=None,
    lanes: int = TUNE_LANES,
    reps: int = TUNE_REPS,
    base: KernelConfig | None = None,
    observer=None,
):
    """Greedily tune each applicable candidate axis: apply the value,
    rebuild + compile the axis's bench kernel, time `reps` dispatches
    (min wins), carry the winner into the next axis's baseline.

    Returns (choices, timings, bench_runs) where choices maps field ->
    (winning value, source) and source is "tuned" or "inapplicable".
    """
    obs = observer or (lambda kind, **fields: None)
    cfg = base or KernelConfig()
    choices: dict = {}
    timings: dict = {}
    bench_runs = 0
    for field, cand in (candidates or CANDIDATES).items():
        if not cand.applicable():
            choices[field] = (getattr(cfg, field), "inapplicable")
            continue
        per_value: dict = {}
        for value in cand.values:
            trial = dataclasses.replace(cfg, **{field: value})
            trial.apply()
            run = cand.builder(lanes)
            run()  # compile + warm (absorbed by the persistent cache)
            best = min(
                _timed(run) for _ in range(max(1, reps))
            )
            per_value[_label(value)] = best
            bench_runs += 1
            obs("bench", axis=field, choice=_label(value), seconds=best)
        win = min(cand.values, key=lambda v: per_value[_label(v)])
        cfg = dataclasses.replace(cfg, **{field: win})
        choices[field] = (win, "tuned")
        timings[field] = per_value
    return choices, timings, bench_runs


def _timed(run: Callable[[], None]) -> float:
    t0 = time.perf_counter()
    run()
    return time.perf_counter() - t0


def aot_prewarm(
    config: KernelConfig | None = None,
    lanes=PREWARM_LANES,
    candidates=None,
    observer=None,
) -> list[tuple[str, int, float]]:
    """Lower + compile the CHOSEN kernel variants across the prewarm
    shape ladder so the persistent compilation cache (jaxcache.py)
    absorbs the binaries. Cold, each entry pays a real XLA compile;
    warm, the same call replays as cache loads — which is the whole
    artifact story. Returns [(axis, bucket_lanes, seconds)]."""
    from charon_tpu.ops import blsops

    obs = observer or (lambda kind, **fields: None)
    if config is not None:
        config.apply()
    report = []
    for field, cand in (candidates or CANDIDATES).items():
        if not cand.applicable():
            continue
        for n in lanes:
            t0 = time.perf_counter()
            cand.builder(n)()
            dt = time.perf_counter() - t0
            bucket = blsops.bucket_lanes(n)
            report.append((field, bucket, dt))
            obs("prewarm", axis=field, lanes=bucket, seconds=dt)
    return report


# ---------------------------------------------------------------------------
# Profile persistence
# ---------------------------------------------------------------------------


def profile_schema() -> dict:
    """Current profile schema snapshot (tests/testdata/autotune_schema
    .json holds the blessed copy; compare_profile_schema gates it)."""
    return {
        "version": PROFILE_VERSION,
        "fields": list(PROFILE_FIELDS),
        "required": list(PROFILE_REQUIRED),
    }


def compare_profile_schema(golden: dict, current: dict) -> list[str]:
    """Append-only contract between profile writers and readers, in the
    analysis/schema_check.py style: a non-empty return is the CI
    failure message."""
    errs: list[str] = []
    gv, cv = int(golden["version"]), int(current["version"])
    if cv < gv:
        errs.append(f"profile schema version regressed: {gv} -> {cv}")
    gf, cf = list(golden["fields"]), list(current["fields"])
    if cf[: len(gf)] != gf:
        errs.append(
            "profile fields removed or reordered (append-only): "
            f"{gf} -> {cf}"
        )
    added_req = set(current["required"]) - set(golden["required"])
    if added_req and cv == gv:
        errs.append(
            f"new required field(s) {sorted(added_req)} need a schema "
            "version bump (old writers omit them)"
        )
    return errs


def fingerprint() -> dict:
    """The profile staleness key: platform + jax version + the SAME
    ops/mesh source digest the blessed kernel manifest is keyed by
    (analysis/jaxpr_check.source_digest — reused, not duplicated), plus
    the informational host fingerprint."""
    import jax

    from charon_tpu import jaxcache
    from charon_tpu.analysis.jaxpr_check import source_digest

    return {
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "source_digest": source_digest(),
        "host": jaxcache.host_fingerprint(),
    }


def default_profile_path() -> Path:
    """Next to the jit cache for this platform (jaxcache placement
    rules: CPU dirs are host-fingerprinted, TPU shares one dir)."""
    import jax

    from charon_tpu import jaxcache

    cpu = jax.default_backend() == "cpu"
    return Path(jaxcache.cache_dir(cpu)) / PROFILE_BASENAME


def load_profile(path) -> dict:
    """Read + validate a persisted profile. Raises ProfileError (typed;
    `reason` attribute) — never returns a half-usable dict."""
    p = Path(path)
    try:
        raw = p.read_text()
    except FileNotFoundError:
        raise ProfileError("missing", f"no kernel profile at {p}") from None
    except OSError as e:
        raise ProfileError("unreadable", f"kernel profile {p}: {e}") from e
    try:
        prof = json.loads(raw)
    except ValueError as e:
        raise ProfileError(
            "corrupt", f"kernel profile {p} is not valid JSON: {e}"
        ) from e
    if not isinstance(prof, dict):
        raise ProfileError("corrupt", f"kernel profile {p}: not an object")
    missing = [f for f in PROFILE_REQUIRED if f not in prof]
    if missing:
        raise ProfileError(
            "schema", f"kernel profile {p} missing fields {missing}"
        )
    if not isinstance(prof["version"], int) or prof["version"] < 1:
        raise ProfileError(
            "schema", f"kernel profile {p}: bad version {prof['version']!r}"
        )
    if prof["version"] > PROFILE_VERSION:
        raise ProfileError(
            "version",
            f"kernel profile {p} is v{prof['version']} (this build reads "
            f"<= v{PROFILE_VERSION})",
        )
    cfg = prof["config"]
    known = {f.name for f in dataclasses.fields(KernelConfig)}
    if not isinstance(cfg, dict) or not set(cfg) <= known:
        raise ProfileError(
            "schema", f"kernel profile {p}: bad config block {cfg!r}"
        )
    for k, v in cfg.items():
        if v is not None and not isinstance(v, bool):
            raise ProfileError(
                "schema", f"kernel profile {p}: config.{k}={v!r} not bool"
            )
    return prof


def save_profile(prof: dict, path) -> None:
    """Atomic write (tmp + rename) — a crash mid-save must leave either
    the old profile or none, never a truncated one. The tmp name is
    per-writer (pid): two nodes cold-booting against a shared cache dir
    must not interleave write_text/os.replace on ONE tmp file and
    publish a torn profile."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(f"{p.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(json.dumps(prof, indent=1, sort_keys=True) + "\n")
        os.replace(tmp, p)
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise


def staleness(prof: dict, fp: dict | None = None) -> str | None:
    """Why a loaded profile cannot be trusted on this boot (None =
    fresh). Host is informational only — kernel CHOICE is a platform
    fact, unlike the host-keyed XLA:CPU AOT artifacts."""
    fp = fp or fingerprint()
    for key in ("platform", "jax_version", "source_digest"):
        if prof.get(key) != fp[key]:
            return key
    return None


def prewarm_marker_path(path=None) -> Path:
    """The prewarm-completion marker lives NEXT TO the profile (same
    placement override rules), so wiping the cache dir wipes both."""
    p = Path(path) if path else default_profile_path()
    return p.with_name(PREWARM_MARKER_BASENAME)


def mark_prewarmed(path=None) -> Path:
    """Record that a crypto-plane prewarm COMPLETED under the current
    fingerprint (app/run.py writes this after a successful
    `crypto_plane.prewarm()`). This is the evidence `warm_boot_ready`
    needs: a fresh tuned profile only proves the tuner's micro-bench
    kernels are in the compile cache — the minutes-long duty pairing
    programs land there only once a real prewarm (or explicit
    `--crypto-plane-prewarm on` boot) has run to completion."""
    m = prewarm_marker_path(path)
    save_profile({"version": PROFILE_VERSION, **fingerprint()}, m)
    return m


def _read_marker(m: Path) -> dict | None:
    try:
        d = json.loads(m.read_text())
    except (OSError, ValueError):
        return None
    return d if isinstance(d, dict) else None


def warm_boot_ready(path=None) -> bool:
    """True when a fresh tuned profile AND a same-fingerprint prewarm
    marker exist — the signal that makes `--crypto-plane-prewarm auto`
    worthwhile off-TPU (app/run.py): prewarm then replays the duty
    pairing programs as cache loads, not compiles. A non-empty cache
    dir is NOT enough: after a first tuned boot it holds only the
    tuner's micro-bench/prewarm kernels, and flipping prewarm on would
    pay the full XLA:CPU pairing compiles the auto gate exists to
    avoid. The marker is written only after a prewarm actually
    completed (mark_prewarmed), and a platform/jax/source-digest change
    distrusts it exactly like the profile."""
    try:
        fp = fingerprint()
        p = Path(path) if path else default_profile_path()
        if staleness(load_profile(p), fp) is not None:
            return False
        mark = _read_marker(prewarm_marker_path(path))
        return mark is not None and staleness(mark, fp) is None
    except (ImportError, ProfileError, OSError):
        return False


# ---------------------------------------------------------------------------
# The startup resolver
# ---------------------------------------------------------------------------


def resolve(
    mode: str = "auto",
    path=None,
    *,
    observer=None,
    lanes: int = TUNE_LANES,
    reps: int = TUNE_REPS,
    candidates=None,
    bench=None,
    environ=None,
) -> TuneResult:
    """Resolve the kernel config for this boot and APPLY it.

    mode: "off" = defaults + env overrides, no profile IO, no bench;
    "auto"/"on" = load a fresh profile (pure load, zero bench runs) or
    micro-bench and persist one; "force" = always re-tune (re-bless a
    suspicious profile). On hosts without jax, "auto" skips loudly and
    "on"/"force" raise PlaneConfigError.

    `observer(kind, **fields)` receives "profile" (event=hit|miss|
    stale|corrupt|rebuilt|off|skipped), "decision" (axis/choice/
    source), "bench" and "prewarm" events — app/metrics.autotune_hook
    adapts them onto the counter families. `bench` injects a
    micro_bench-compatible callable (tests).
    """
    from charon_tpu.core.cryptoplane import PlaneConfigError

    global _ENV_WARNED
    if mode not in ("auto", "on", "off", "force"):
        raise PlaneConfigError(f"unknown autotune mode {mode!r}")
    obs = observer or (lambda kind, **fields: None)
    overrides = env_overrides(environ)
    if overrides and not _ENV_WARNED:
        _ENV_WARNED = True
        log.warn(
            "CHARON_MSM/CHARON_MXU_MONT env toggles are deprecated; they "
            "now act as KernelConfig overrides that outrank the tuned "
            "profile — prefer --crypto-autotune / set_* for harnesses",
            topic="autotune",
            overrides={k: v for k, v in sorted(overrides.items())},
        )
    sources = {f: "default" for f in KernelConfig.TUNABLE}

    if mode == "off":
        cfg = dataclasses.replace(KernelConfig(), **overrides)
        applied = cfg.apply()
        sources.update({f: "env" for f in overrides})
        obs("profile", event="off")
        _emit_decisions(obs, cfg, sources)
        return TuneResult(
            config=cfg,
            outcome="off",
            applied=applied,
            bench_runs=0,
            sources=sources,
            timings={},
            overrides=overrides,
            profile_path=None,
        )

    try:
        from charon_tpu.core.cryptoplane import kernel_inventory

        families = sorted(kernel_inventory())
        fp = fingerprint()
    except (ImportError, PlaneConfigError) as e:
        if mode in ("on", "force"):
            raise PlaneConfigError(
                f"--crypto-autotune {mode} requires the device stack: {e}"
            ) from e
        log.warn(
            "kernel auto-tune skipped: device stack unavailable on this "
            "host; running KernelConfig defaults",
            topic="autotune",
            err=str(e),
        )
        cfg = dataclasses.replace(KernelConfig(), **overrides)
        sources.update({f: "env" for f in overrides})
        obs("profile", event="skipped")
        _emit_decisions(obs, cfg, sources)
        return TuneResult(
            config=cfg,
            outcome="skipped",
            applied=cfg.apply(),
            bench_runs=0,
            sources=sources,
            timings={},
            overrides=overrides,
            profile_path=None,
        )

    p = Path(path) if path else default_profile_path()
    prof = None
    if mode != "force":
        try:
            prof = load_profile(p)
        except ProfileError as e:
            if e.reason == "missing":
                obs("profile", event="miss")
            else:
                log.warn(
                    "kernel profile unusable; re-tuning",
                    topic="autotune",
                    path=str(p),
                    reason=e.reason,
                    err=str(e),
                )
                obs("profile", event="corrupt")
        if prof is not None:
            stale = staleness(prof, fp)
            if stale is not None:
                log.info(
                    "kernel profile stale; re-tuning",
                    topic="autotune",
                    path=str(p),
                    key=stale,
                )
                obs("profile", event="stale")
                prof = None

    timings: dict = {}
    bench_runs = 0
    if prof is not None:
        obs("profile", event="hit")
        outcome = "hit"
        cfg = dataclasses.replace(
            KernelConfig(), **{k: v for k, v in prof["config"].items()}
        )
        sources.update({f: "profile" for f in KernelConfig.TUNABLE})
        timings = prof.get("timings", {})
    else:
        run_bench = bench or micro_bench
        choices, timings, bench_runs = run_bench(
            candidates=candidates,
            lanes=lanes,
            reps=reps,
            base=KernelConfig(),
            observer=obs,
        )
        cfg = dataclasses.replace(
            KernelConfig(), **{f: v for f, (v, _src) in choices.items()}
        )
        sources.update({f: src for f, (_v, src) in choices.items()})
        prof = dict(
            version=PROFILE_VERSION,
            **fp,
            config=cfg.as_dict(),
            sources={f: sources[f] for f in KernelConfig.TUNABLE},
            timings=timings,
            families=families,
            tune_lanes=lanes,
            prewarm_lanes=list(PREWARM_LANES),
        )
        save_profile(prof, p)
        obs("profile", event="rebuilt")
        outcome = "tuned"

    # deploy-pinned env overrides outrank whatever won above
    cfg = dataclasses.replace(cfg, **overrides)
    sources.update({f: "env" for f in overrides})
    applied = cfg.apply()
    _emit_decisions(obs, cfg, sources)
    return TuneResult(
        config=cfg,
        outcome=outcome,
        applied=applied,
        bench_runs=bench_runs,
        sources=sources,
        timings=timings,
        overrides=overrides,
        profile_path=str(p),
    )


def _emit_decisions(obs, cfg: KernelConfig, sources: dict) -> None:
    for field in KernelConfig.TUNABLE:
        obs(
            "decision",
            axis=field,
            choice=_label(getattr(cfg, field)),
            source=sources.get(field, "default"),
        )
