"""Wire frames + framing for the remote crypto-plane service (ISSUE 17).

The in-process `core/cryptosvc.CryptoPlaneService` becomes dialable: a
physically separate DV cluster submits verify/recombine jobs over a TCP
socket speaking the PR 7 binary codec. This module is the shared
vocabulary of `cryptosvc_server` and `cryptosvc_client`:

  * the RPC frame dataclasses (append-only wire ids 21..27 in
    `p2p/codec._TYPE_WIRE_IDS`, blessed into the wire-schema golden);
  * length-prefixed framing identical to `p2p/transport._write_frame` /
    `_read_frame` (4-byte big-endian length, 128 MB cap) — reimplemented
    here rather than imported because `p2p/transport` pulls in
    `app.k1util` (the `cryptography` package), which minimal images and
    this service deliberately do not require;
  * envelope version negotiation reusing the transport's convention:
    the handshake frames always ride the JSON envelope (sniffable with
    zero per-connection state), each side advertises its
    `WIRE_VERSION`, and post-handshake frames use
    `min(ours, theirs)` — binary v1 when both sides speak it;
  * challenge/response tenant auth: the server sends a fresh nonce, the
    client proves knowledge of its tenant token with an HMAC-SHA256 over
    it. The token itself NEVER crosses the wire (and never reaches
    logs, reprs, or metrics labels — analysis/rule_secret_flow.py lints
    the `auth_token` name as a secret source).

Deadlines travel RELATIVE (`deadline_rel` = seconds until the wall-clock
duty deadline at send time): absolute `time.time()` values are
meaningless across hosts with skewed clocks, and the PR 8 `_arm`
wall/monotonic confusion is exactly the bug class this avoids repeating
across machines. FlushStats stage spans ride results the same way
(offsets back from the server's send instant) for the same reason.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
from dataclasses import dataclass

from charon_tpu.p2p.codec import (
    CodecError,
    decode_envelope,
    encode_envelope,
    register,
)

PROTOCOL = "cryptosvc/1"
# Highest binary envelope this build speaks (mirrors
# p2p.transport.WIRE_VERSION; 0 = JSON-only)
WIRE_VERSION = 1
MAX_FRAME = 128 * 1024 * 1024  # same cap as p2p/transport.MAX_FRAME
HELLO_TIMEOUT = 5.0


@register
@dataclass(frozen=True)
class CryptoChallenge:
    """Server -> client, immediately on accept: the auth nonce (public
    by construction) plus the server's wire-version advertisement."""

    nonce: bytes
    wire: int = WIRE_VERSION


@register
@dataclass(frozen=True)
class CryptoHello:
    """Client -> server: tenant identity + HMAC proof over the nonce."""

    tenant_id: str
    proof: bytes
    wire: int = WIRE_VERSION


@register
@dataclass(frozen=True)
class CryptoHelloAck:
    """Server -> client: auth verdict + negotiated wire version + the
    service plane's threshold and heartbeat cadence."""

    ok: bool
    wire: int = 0
    t: int = 0
    heartbeat: float = 1.0
    error: str = ""


@register
@dataclass(frozen=True)
class CryptoSubmit:
    """One verify/recombine job. `args` mirrors
    `CryptoPlaneService.submit` args (lists of bytes/int rows);
    `deadline_rel` is seconds-until-deadline at send time, or None."""

    job_id: int
    kind: str  # "verify" | "recombine"
    args: tuple
    lanes: int
    deadline_rel: float | None = None


@register
@dataclass(frozen=True)
class CryptoResult:
    """Job completion. `value` is the plane result (verify: [bool] per
    lane; recombine: [[sig|None...], [ok...]]). `error_kind` separates
    crypto verdicts ("tbls" — identical on every rung, the client must
    NOT fail over) from infrastructure faults ("error" — the client
    degrades to its local ladder). `stats` is the compact cross-process
    FlushStats attribution dict (see cryptosvc_server._flush_brief)."""

    job_id: int
    value: object = None
    error: str = ""
    error_kind: str = ""  # "" | "tbls" | "error"
    stats: dict | None = None


@register
@dataclass(frozen=True)
class CryptoShed:
    """Server-side admission rejection: the tenant's queue is over
    quota (`core/cryptosvc.PlaneOverloadError` crossing the wire)."""

    job_id: int
    reason: str  # "jobs" | "lanes" | "closed"
    detail: str = ""


@register
@dataclass(frozen=True)
class CryptoHeartbeat:
    """Liveness probe. The client sends seq, the server echoes it back
    with echo=True; the client pins miss detection to time.monotonic."""

    seq: int
    echo: bool = False


def auth_proof(auth_token: bytes, nonce: bytes) -> bytes:
    """HMAC-SHA256 proof of token knowledge over the server's nonce."""
    return hmac.new(auth_token, nonce, hashlib.sha256).digest()


def proof_ok(auth_token: bytes, nonce: bytes, proof: bytes) -> bool:
    """Constant-time proof check (never log either side's inputs)."""
    return hmac.compare_digest(auth_proof(auth_token, nonce), proof)


def send_frame(
    writer: asyncio.StreamWriter, msg, binary: bool
) -> None:
    """Encode + write one service frame. Fully synchronous (two
    buffered writes, no await) so concurrent sender tasks on one
    connection can never interleave a header with another frame's
    payload; callers drain() afterwards."""
    payload = encode_envelope(PROTOCOL, "", "req", msg, binary)
    if len(payload) > MAX_FRAME:
        raise CodecError("service frame exceeds max size")
    writer.write(len(payload).to_bytes(4, "big"))
    writer.write(payload)


async def read_frame(reader: asyncio.StreamReader):
    """Read + decode one service frame. Raises CodecError on any
    malformation (oversize, bad envelope, wrong protocol) and the
    usual ConnectionError/IncompleteReadError on socket death."""
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > MAX_FRAME:
        raise CodecError("oversized service frame")
    payload = await reader.readexactly(length)
    env = decode_envelope(payload)
    if env["p"] != PROTOCOL:
        raise CodecError(f"unexpected service protocol {env['p']!r}")
    return env["d"]
