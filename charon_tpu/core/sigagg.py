"""SigAgg: threshold aggregation of partial signatures — the hot path.

Mirrors ref: core/sigagg/sigagg.go:84-122 (Lagrange recombination via
tbls.ThresholdAggregate, then verification of the recovered group
signature, sigagg.go:117) — but batch-first: a whole duty's pubkeys are
recombined in ONE device program and verified in ONE device program via
the tbls batch API, instead of the reference's per-pubkey herumi calls.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Awaitable, Callable, Mapping

from charon_tpu import tbls
from charon_tpu.core.eth2data import ParSignedData, SignedData
from charon_tpu.core.types import Duty, PubKey, pubkey_to_bytes
from charon_tpu.eth2util.signing import ForkInfo

AggSub = Callable[[Duty, dict[PubKey, SignedData]], Awaitable[None]]


class AggregationError(Exception):
    pass


@dataclass
class SigAgg:
    """threshold: cluster threshold t; fork/epoch context for signing roots."""

    threshold: int
    fork: ForkInfo
    slots_per_epoch: int = 32

    def __post_init__(self) -> None:
        self._subs: list[AggSub] = []

    def subscribe(self, sub: AggSub) -> None:
        self._subs.append(sub)

    async def aggregate(
        self, duty: Duty, batch: Mapping[PubKey, list[ParSignedData]]
    ) -> None:
        if not batch:
            return
        epoch = duty.slot // self.slots_per_epoch

        pubkeys: list[PubKey] = []
        partial_maps: list[dict[int, bytes]] = []
        templates: list[ParSignedData] = []
        for pubkey, psigs in batch.items():
            if len(psigs) < self.threshold:
                raise AggregationError(
                    f"insufficient partial signatures for {duty}/{pubkey}"
                )
            use = psigs[: self.threshold]
            pubkeys.append(pubkey)
            partial_maps.append(
                {p.share_idx: p.data.signature for p in use}
            )
            templates.append(use[0])

        # ONE device program recombines every pubkey's partials
        # (ref equivalent: sigagg.go:104 per-pubkey tbls.ThresholdAggregate).
        group_sigs = tbls.threshold_aggregate_batch(partial_maps)

        # ONE device program verifies all recovered signatures
        # (ref equivalent: sigagg.go:117 per-pubkey verify).
        items = []
        for pubkey, template, sig in zip(pubkeys, templates, group_sigs):
            root = template.data.signing_root(self.fork, epoch)
            items.append((pubkey_to_bytes(pubkey), root, sig))
        ok = tbls.verify_batch(items)
        bad = [str(pk) for pk, o in zip(pubkeys, ok) if not o]
        if bad:
            raise AggregationError(
                f"recovered group signature failed verification for {bad}"
            )

        out = {
            pk: tmpl.data.with_signature(sig)
            for pk, tmpl, sig in zip(pubkeys, templates, group_sigs)
        }
        for sub in self._subs:
            await sub(duty, out)
