"""SigAgg: threshold aggregation of partial signatures — the hot path.

Mirrors ref: core/sigagg/sigagg.go:84-122 (Lagrange recombination via
tbls.ThresholdAggregate, then verification of the recovered group
signature, sigagg.go:117) — but batch-first: a whole duty's pubkeys are
recombined in ONE device program and verified in ONE device program via
the tbls batch API, instead of the reference's per-pubkey herumi calls.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Awaitable, Callable, Mapping

from charon_tpu import tbls
from charon_tpu.core.eth2data import ParSignedData, SignedData
from charon_tpu.core.types import Duty, PubKey, pubkey_to_bytes
from charon_tpu.eth2util.signing import ForkInfo

AggSub = Callable[[Duty, dict[PubKey, SignedData]], Awaitable[None]]


class AggregationError(Exception):
    pass


@dataclass
class SigAgg:
    """threshold: cluster threshold t; fork/epoch context for signing roots.

    plane + pubshares_by_idx (both or neither): route recombination AND
    group verification through the core.cryptoplane.SlotCoalescer — one
    sharded device program per coalescing window, merged with every other
    duty's concurrent work. Without a plane, the tbls batch API executes
    this duty's batch alone (still one program per duty, the round-2
    design)."""

    threshold: int
    fork: ForkInfo
    slots_per_epoch: int = 32
    plane: object | None = None  # core.cryptoplane.SlotCoalescer
    pubshares_by_idx: Mapping[int, Mapping[PubKey, bytes]] | None = None
    # optional core.deadline.SlotClock: plane submissions carry the
    # duty's expiry so the coalescer's adaptive window shrinks instead
    # of overshooting a near-deadline aggregation
    clock: object | None = None
    # optional core/evidence.EvidenceRegistry: lanes from peers with
    # equivocation-class evidence (EXCLUSION_KINDS) are excluded from
    # recombination while >= threshold clean lanes remain — the per-peer
    # quarantine primitive applied to the aggregation path
    evidence: object | None = None

    def __post_init__(self) -> None:
        self._subs: list[AggSub] = []
        self.excluded_lanes = 0  # partials dropped on evidence
        self.exclusion_fallbacks = 0  # exclusions waived for liveness

    def subscribe(self, sub: AggSub) -> None:
        self._subs.append(sub)

    async def aggregate(
        self, duty: Duty, batch: Mapping[PubKey, list[ParSignedData]]
    ) -> None:
        if not batch:
            return
        epoch = duty.slot // self.slots_per_epoch

        excluded = (
            self.evidence.excluded_shares()
            if self.evidence is not None
            else ()
        )

        pubkeys: list[PubKey] = []
        partial_maps: list[dict[int, bytes]] = []
        templates: list[ParSignedData] = []
        for pubkey, psigs in batch.items():
            if len(psigs) < self.threshold:
                raise AggregationError(
                    f"insufficient partial signatures for {duty}/{pubkey}"
                )
            use = psigs
            if excluded:
                clean = [
                    p for p in psigs if p.share_idx not in excluded
                ]
                if len(clean) >= self.threshold:
                    self.excluded_lanes += len(psigs) - len(clean)
                    use = clean
                else:
                    # liveness over suspicion: with fewer than t clean
                    # lanes the duty would fail outright — recombine from
                    # what arrived and let group verification arbitrate
                    # (>= t honest peers always supply t clean lanes when
                    # adversaries <= f, so this fires only under extra
                    # crash/partition faults)
                    self.exclusion_fallbacks += 1
            use = use[: self.threshold]
            pubkeys.append(pubkey)
            partial_maps.append(
                {p.share_idx: p.data.signature for p in use}
            )
            templates.append(use[0])

        if self.plane is not None and self.pubshares_by_idx is not None:
            group_sigs = await self._aggregate_via_plane(
                duty, epoch, pubkeys, partial_maps, templates
            )
        else:
            # plane-less rung: deliberately INLINE (see ValidatorAPI.
            # _check_batch — the executor hop GIL-convoys the loop and
            # distorts duty timing); production wires the plane, and
            # the overload-shed branch in _aggregate_via_plane runs
            # off-loop where it matters
            group_sigs = self._aggregate_via_tbls(  # lint: allow(event-loop-blocking)
                epoch, pubkeys, partial_maps, templates
            )

        out = {
            pk: tmpl.data.with_signature(sig)
            for pk, tmpl, sig in zip(pubkeys, templates, group_sigs)
        }
        for sub in self._subs:
            await sub(duty, out)

    def _aggregate_via_tbls(
        self, epoch, pubkeys, partial_maps, templates
    ) -> list[bytes]:
        # ONE device program recombines every pubkey's partials
        # (ref equivalent: sigagg.go:104 per-pubkey tbls.ThresholdAggregate).
        group_sigs = tbls.threshold_aggregate_batch(partial_maps)

        # ONE device program verifies all recovered signatures
        # (ref equivalent: sigagg.go:117 per-pubkey verify).
        items = []
        for pubkey, template, sig in zip(pubkeys, templates, group_sigs):
            root = template.data.signing_root(self.fork, epoch)
            items.append((pubkey_to_bytes(pubkey), root, sig))
        ok = tbls.verify_batch(items)
        bad = [str(pk) for pk, o in zip(pubkeys, ok) if not o]
        if bad:
            raise AggregationError(
                f"recovered group signature failed verification for {bad}"
            )
        return group_sigs

    async def _aggregate_via_plane(
        self, duty, epoch, pubkeys, partial_maps, templates
    ) -> list[bytes]:
        # One [V, t] recombine+verify job; the coalescer merges it with
        # any other duty's job in the same window into ONE sharded
        # program (recombination, per-partial verify against pubshares,
        # and group-sig verify all inside — SlotCryptoPlane.local_step).
        ps_rows, roots, sig_rows, gpks, idx_rows = [], [], [], [], []
        for pubkey, template, pmap in zip(pubkeys, templates, partial_maps):
            idx = sorted(pmap)
            try:
                ps_rows.append(
                    [self.pubshares_by_idx[i][pubkey] for i in idx]
                )
            except KeyError as e:
                raise AggregationError(
                    f"no pubshare for {pubkey} share {e}"
                ) from e
            roots.append(template.data.signing_root(self.fork, epoch))
            sig_rows.append([pmap[i] for i in idx])
            gpks.append(pubkey_to_bytes(pubkey))
            idx_rows.append(idx)
        kwargs = {}
        if self.clock is not None:
            kwargs["deadline"] = self.clock.duty_deadline(duty)
        from charon_tpu.core.cryptosvc import PlaneOverloadError

        try:
            group_sigs, ok = await self.plane.recombine(
                ps_rows, roots, sig_rows, gpks, idx_rows, **kwargs
            )
        except PlaneOverloadError:
            # admission shed (core/cryptosvc backpressure): recombine
            # THIS duty on the host tbls rung, on an executor thread —
            # the aggregation ladder absorbs shed load instead of
            # failing the duty, and the host pairing math never stalls
            # the event loop
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None,
                self._aggregate_via_tbls,
                epoch, pubkeys, partial_maps, templates,
            )
        bad = [str(pk) for pk, o in zip(pubkeys, ok) if not o]
        if bad:
            raise AggregationError(
                f"recovered group signature failed verification for {bad}"
            )
        return group_sigs
