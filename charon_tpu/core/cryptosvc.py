"""Multi-tenant crypto-plane service: one device mesh, many clusters.

The ROADMAP's "millions of users" regime is N independent DV clusters
sharing one TPU mesh (open item 4): the `SlotCoalescer` already
pipelines, buckets, prewarms and degrades gracefully, but it trusts its
submitters — any caller can flood the coalescing window, and a tenant
whose lanes persistently fail verification dilutes every other tenant's
RLC batches. Handel (arXiv:1906.05132) and aggregated-signature gossip
BFT (arXiv:1911.04698) both assume cheap bulk verification *surviving
byzantine load*; the RLC batches provide the "cheap", this boundary
provides the "surviving":

  * **per-tenant submission queues with deadline-aware weighted-fair
    scheduling** — duty deadlines already travel on submissions; the
    dispatcher admits work into the shared coalescer earliest-deadline-
    first *within a per-tenant lane quota per scheduling round* (round
    length = the coalescing window), so a starved tenant's near-deadline
    duty preempts a flooder's backlog instead of queueing behind it;
  * **admission control / backpressure** — bounded queue depth (jobs AND
    lanes, counting in-flight work) per tenant; over-budget submissions
    fail fast with the typed `PlaneOverloadError`, which the submitters'
    existing degradation ladder (parsigex / sigagg / validatorapi)
    catches and serves from the host tbls rung — shed load costs the
    flooder latency, never the event loop a deadlock;
  * **per-tenant circuit breaker** — a tenant whose lanes persistently
    fail verification (forged-signature flood) is *quarantined to its
    own flushes*: while the breaker is open its submissions route to a
    dedicated per-tenant coalescer sharing the same device plane, so a
    forged batch can never force an RLC retry-split or false-reject on
    an honest tenant's lanes sharing the window. After a cooldown the
    breaker half-opens; one fully-clean quarantined flush closes it.

The service is a *narrow* boundary: components hold a `TenantPlane`
handle exposing exactly the coalescer surface they already use
(`t`, `verify`, `recombine`), so `SigAgg` / `Eth2Verifier` /
`ValidatorAPI` are tenant-agnostic. Everything here is event-loop-side
bookkeeping (heaps and counters); the crypto stays in the coalescer.

Observability: `observer(kind, tenant, **fields)` receives typed events
("shed", "dispatch", "complete", "breaker", "queue") — app/metrics.py
`tenant_hook()` turns them into the tenant-labeled metric families, and
per-flush tenant attribution rides `FlushStats.tenant_lanes`.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from dataclasses import dataclass, field

from charon_tpu.tbls import TblsError


class TenantConfigError(ValueError):
    """Invalid service wiring (duplicate tenant registration etc.) —
    a deploy/programming bug, typed so the plane's load-shedding
    handlers (which catch TblsError) never mistake it for overload."""


class PlaneOverloadError(TblsError):
    """Typed fail-fast admission rejection: the tenant's submission
    queue is over its configured depth. A TblsError subclass so generic
    crypto-error handling degrades instead of crashing, but submitters
    catch it SPECIFICALLY and route the shed work to their host tbls
    rung — the caller must never block on an overloaded plane."""

    def __init__(self, tenant: str, reason: str, detail: str = ""):
        self.tenant = tenant
        self.reason = reason  # "jobs" | "lanes" | "closed"
        msg = f"crypto plane overloaded for tenant {tenant!r} ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission and fairness knobs (docs/operations.md
    "Multi-tenant deployment" explains how to size them)."""

    # relative share of the service's per-round lane budget (weighted
    # fair: budget_i = round_lanes * weight_i / sum(weights))
    weight: float = 1.0
    # admission bounds: queued + in-flight submissions/lanes; beyond
    # either, new submissions shed with PlaneOverloadError
    max_queue_jobs: int = 256
    max_queue_lanes: int = 4096
    # circuit breaker: open when, over the last breaker_window lanes
    # (>= breaker_min_lanes seen), the failed-verification ratio
    # reaches breaker_threshold; half-open after breaker_cooldown s
    breaker_window: int = 128
    breaker_min_lanes: int = 32
    breaker_threshold: float = 0.5
    breaker_cooldown: float = 5.0


class CircuitBreaker:
    """Per-tenant forged-flood breaker over lane verification outcomes.

    closed -> open when the rolling failure ratio trips the threshold;
    open -> half_open after the cooldown; one fully-clean quarantined
    flush closes it, any failed lane re-opens (cooldown restarts).
    Lane outcomes recorded while open are ignored — an open breaker is
    already quarantined, and its backlog draining with failures must
    not keep resetting the window state."""

    def __init__(self, quota: TenantQuota, on_transition=None):
        self.quota = quota
        self.state = "closed"
        self.opened_at = 0.0
        self._window: list[tuple[int, int]] = []  # (ok, failed) per flush
        self._window_lanes = 0
        self._window_failed = 0
        self.transitions: dict[str, int] = {}
        self._on_transition = on_transition

    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        self.transitions[state] = self.transitions.get(state, 0) + 1
        if state == "open":
            self.opened_at = time.monotonic()
        if state in ("open", "closed"):
            self._window.clear()
            self._window_lanes = self._window_failed = 0
        if self._on_transition is not None:
            self._on_transition(state)

    def quarantined(self) -> bool:
        """True when this tenant's dispatches must use its own flushes.
        Checking also advances open -> half_open past the cooldown."""
        if self.state == "open" and (
            time.monotonic() - self.opened_at >= self.quota.breaker_cooldown
        ):
            self._transition("half_open")
        return self.state != "closed"

    def record(self, ok: int, failed: int) -> None:
        """Lane outcomes of one completed dispatch."""
        if self.state == "open":
            return
        if self.state == "half_open":
            # the probe verdict: one clean quarantined flush closes the
            # breaker, any forged lane re-opens it for another cooldown
            self._transition("closed" if failed == 0 else "open")
            return
        self._window.append((ok, failed))
        self._window_lanes += ok + failed
        self._window_failed += failed
        while (
            self._window
            and self._window_lanes - sum(self._window[0])
            >= self.quota.breaker_window
        ):
            o, f = self._window.pop(0)
            self._window_lanes -= o + f
            self._window_failed -= f
        if (
            self._window_lanes >= self.quota.breaker_min_lanes
            and self._window_failed
            >= self.quota.breaker_threshold * self._window_lanes
        ):
            self._transition("open")


@dataclass
class _Entry:
    kind: str  # "verify" | "recombine"
    args: tuple
    lanes: int
    deadline: float | None  # wall clock (time.time), as submitted
    fut: asyncio.Future
    seq: int


class _Tenant:
    def __init__(self, tenant_id: str, quota: TenantQuota, on_breaker=None):
        self.id = tenant_id
        self.quota = quota
        self.queue: list[tuple[float, int, _Entry]] = []  # (edf key, seq, e)
        self.pending_jobs = 0  # queued + dispatched, until completion
        self.pending_lanes = 0
        self.breaker = CircuitBreaker(quota, on_transition=on_breaker)
        self.quarantine_coal = None  # lazy SlotCoalescer for open-breaker
        # observability counters (scenario tests + /metrics attribution)
        self.shed: dict[str, int] = {}
        self.shed_lanes = 0
        self.admitted_jobs = 0
        self.admitted_lanes = 0
        self.completed_lanes = 0
        self.failed_lanes = 0
        self.quarantined_flushes = 0


class TenantPlane:
    """The narrow per-tenant handle components hold in place of the raw
    coalescer — same duck type (`t`, `verify`, `recombine`), tenant
    identity bound once at registration."""

    def __init__(self, svc: "CryptoPlaneService", tenant_id: str):
        self._svc = svc
        self.tenant_id = tenant_id

    @property
    def t(self) -> int:
        return self._svc.t

    async def verify(self, items, deadline: float | None = None):
        return await self._svc.submit(
            self.tenant_id, "verify", (list(items),), len(items), deadline
        )

    async def recombine(
        self, pubshares, roots, partials, group_pks, indices,
        deadline: float | None = None,
    ):
        rows = (
            list(pubshares), list(roots), list(partials),
            list(group_pks), list(indices),
        )
        return await self._svc.submit(
            self.tenant_id, "recombine", rows, len(rows[1]), deadline
        )


class CryptoPlaneService:
    """One shared SlotCoalescer behind per-tenant admission, fairness,
    and quarantine (module docstring). `round_lanes` is the total lane
    budget a scheduling round may admit across tenants; each tenant's
    share is weight-proportional. `round_interval` defaults to the
    coalescer's base window so one round feeds one coalescing window."""

    def __init__(
        self,
        coalescer,
        round_lanes: int = 4096,
        round_interval: float | None = None,
        observer=None,
        quarantine_window: float = 0.005,
        quarantine_factory=None,  # callable(tenant_id) -> coalescer
    ):
        self._coal = coalescer
        self.round_lanes = round_lanes
        self._round = (
            round_interval
            if round_interval is not None
            else max(float(getattr(coalescer, "window", 0.02)), 0.001)
        )
        self._quarantine_window = quarantine_window
        self._quarantine_factory = quarantine_factory
        self.observer = observer  # callable(kind, tenant, **fields)
        self._tenants: dict[str, _Tenant] = {}
        self._seq = 0
        self._closed = False
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._entry_tasks: set[asyncio.Task] = set()

    # -- registration ------------------------------------------------------

    @property
    def t(self) -> int:
        return self._coal.t

    @property
    def coalescer(self):
        """The shared pooled coalescer (lifecycle hooks: prewarm,
        warm_caches, close all stay on the coalescer itself)."""
        return self._coal

    def register(
        self, tenant_id: str, quota: TenantQuota | None = None
    ) -> TenantPlane:
        if tenant_id in self._tenants:
            raise TenantConfigError(
                f"tenant {tenant_id!r} already registered"
            )
        quota = quota or TenantQuota()

        def on_breaker(state: str, _tid=tenant_id) -> None:
            self._observe("breaker", _tid, state=state)

        self._tenants[tenant_id] = _Tenant(tenant_id, quota, on_breaker)
        return TenantPlane(self, tenant_id)

    def tenant(self, tenant_id: str) -> _Tenant:
        """Tenant bookkeeping (counters, breaker) — observability and
        tests; the scheduling state inside is service-private."""
        return self._tenants[tenant_id]

    def _observe(self, kind: str, tenant: str, **fields) -> None:
        if self.observer is not None:
            try:
                self.observer(kind, tenant, **fields)
            except Exception:  # noqa: BLE001 — observer bugs stay out
                pass  # of the duty path

    # -- submission (event-loop side) --------------------------------------

    async def submit(
        self,
        tenant_id: str,
        kind: str,
        args: tuple,
        lanes: int,
        deadline: float | None,
    ):
        ten = self._tenants[tenant_id]
        if self._closed:
            raise PlaneOverloadError(tenant_id, "closed")
        if lanes == 0:
            # empty submissions short-circuit like the coalescer's own
            return [] if kind == "verify" else ([], [])
        q = ten.quota
        reason = None
        if ten.pending_jobs + 1 > q.max_queue_jobs:
            reason = "jobs"
        elif ten.pending_lanes + lanes > q.max_queue_lanes:
            reason = "lanes"
        if reason is not None:
            # fail FAST: no await between the check and the raise, so
            # an overloaded tenant can never wedge the event loop
            ten.shed[reason] = ten.shed.get(reason, 0) + 1
            ten.shed_lanes += lanes
            self._observe("shed", tenant_id, reason=reason, lanes=lanes)
            raise PlaneOverloadError(
                tenant_id,
                reason,
                f"{ten.pending_jobs} jobs / {ten.pending_lanes} lanes "
                f"pending (+{lanes})",
            )
        loop = asyncio.get_running_loop()
        self._seq += 1
        entry = _Entry(
            kind=kind,
            args=args,
            lanes=lanes,
            deadline=deadline,
            fut=loop.create_future(),
            seq=self._seq,
        )
        key = deadline if deadline is not None else float("inf")
        heapq.heappush(ten.queue, (key, entry.seq, entry))
        ten.pending_jobs += 1
        ten.pending_lanes += lanes
        self._observe(
            "queue", tenant_id,
            jobs=ten.pending_jobs, lanes=ten.pending_lanes,
        )
        self._kick()
        return await entry.fut

    def _kick(self) -> None:
        if self._task is None or self._task.done():
            # fresh Event per dispatcher task: asyncio primitives bind
            # to the running loop, and one service may serve several
            # asyncio.run lifetimes (tests, CLI tools)
            self._wake = asyncio.Event()
            self._task = asyncio.create_task(self._drain())
        else:
            self._wake.set()

    # -- dispatcher --------------------------------------------------------

    def _has_queued(self) -> bool:
        return any(t.queue for t in self._tenants.values())

    def _budget(self, ten: _Tenant) -> int:
        total = sum(t.quota.weight for t in self._tenants.values()) or 1.0
        return max(1, int(self.round_lanes * ten.quota.weight / total))

    def _run_round(self, budgets: dict[str, int], spent: dict[str, int]):
        """Admit everything admissible under the current round budgets,
        earliest-deadline-first ACROSS tenants: at each step the
        globally-nearest deadline among in-budget tenants dispatches,
        so a starved tenant's near-deadline duty preempts a flooder's
        backlog. One oversize submission per tenant per round may
        exceed the budget (a burst larger than the quota must degrade
        to per-round trickle, not starve forever)."""
        while True:
            best = None
            for ten in self._tenants.values():
                # drop entries whose waiter is already gone (tenant
                # crash-loop cancelled the submission mid-queue)
                while ten.queue and ten.queue[0][2].fut.done():
                    _, _, dead = heapq.heappop(ten.queue)
                    ten.pending_jobs -= 1
                    ten.pending_lanes -= dead.lanes
                if not ten.queue:
                    continue
                budgets.setdefault(ten.id, self._budget(ten))
                head = ten.queue[0]
                entry = head[2]
                remaining = budgets[ten.id] - spent.get(ten.id, 0)
                if entry.lanes > remaining and spent.get(ten.id, 0) > 0:
                    continue  # over quota this round; next round
                if best is None or head[:2] < best[0][:2]:
                    best = (head, ten)
            if best is None:
                return
            head, ten = best
            heapq.heappop(ten.queue)
            entry = head[2]
            spent[ten.id] = spent.get(ten.id, 0) + entry.lanes
            ten.admitted_jobs += 1
            ten.admitted_lanes += entry.lanes
            quarantined = ten.breaker.quarantined()
            self._observe(
                "dispatch", ten.id,
                lanes=entry.lanes, quarantined=quarantined,
            )
            task = asyncio.create_task(
                self._run_entry(ten, entry, quarantined)
            )
            self._entry_tasks.add(task)
            task.add_done_callback(self._entry_tasks.discard)

    async def _drain(self) -> None:
        """Dispatcher body: rounds of length `_round`, budgets reset per
        round, mid-round wakes admit fresh submissions immediately with
        whatever budget their tenant has left. Exits when every queue
        drains (a later submission spawns a fresh task)."""
        while not self._closed and self._has_queued():
            budgets: dict[str, int] = {}
            spent: dict[str, int] = {}
            round_end = time.monotonic() + self._round
            self._run_round(budgets, spent)
            while not self._closed:
                remaining = round_end - time.monotonic()
                if remaining <= 0:
                    break
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), remaining)
                except asyncio.TimeoutError:
                    break
                self._run_round(budgets, spent)

    # -- entry execution ---------------------------------------------------

    def _quarantine_coal(self, ten: _Tenant):
        """The tenant's own coalescer (lazy): same plane object, short
        window, no plane_factory (the shared coalescer owns the msm-off
        rung). Its flushes interleave with pooled flushes in the device
        stream exactly like warm-up programs do — acceptable for a
        quarantined minority, and the forged lanes can no longer force
        RLC retries on honest tenants' batches."""
        if ten.quarantine_coal is None:
            if self._quarantine_factory is not None:
                ten.quarantine_coal = self._quarantine_factory(ten.id)
            else:
                from charon_tpu.core.cryptoplane import SlotCoalescer

                # inherit the shared coalescer's RESOLVED decode rung:
                # an operator-forced python mode (or a live device->
                # python step-down) must not be resurrected to 'auto'
                # for exactly the decode-heavy quarantined traffic
                decode_mode = (
                    getattr(self._coal, "_decode_live", None)
                    or getattr(self._coal, "decode_mode", "auto")
                )
                ten.quarantine_coal = SlotCoalescer(
                    self._coal.plane,
                    window=self._quarantine_window,
                    decode_workers=getattr(self._coal, "decode_workers", 0),
                    stats_hook=getattr(self._coal, "stats_hook", None),
                    decode_mode=decode_mode,
                )
        return ten.quarantine_coal

    async def _run_entry(
        self, ten: _Tenant, entry: _Entry, quarantined: bool
    ) -> None:
        t0 = time.monotonic()
        coal = self._quarantine_coal(ten) if quarantined else self._coal
        try:
            if entry.kind == "verify":
                res = await coal.verify(
                    entry.args[0], deadline=entry.deadline, tenant=ten.id
                )
                ok = sum(1 for r in res if r)
                failed = len(res) - ok
            else:
                res = await coal.recombine(
                    *entry.args, deadline=entry.deadline, tenant=ten.id
                )
                oks = res[1]
                ok = sum(1 for r in oks if r)
                failed = len(oks) - ok
        except Exception as e:  # noqa: BLE001 — the coalescer's own
            # ladder already ran; surface the residual to the waiter
            if not entry.fut.done():
                entry.fut.set_exception(e)
            return
        finally:
            ten.pending_jobs -= 1
            ten.pending_lanes -= entry.lanes
        ten.completed_lanes += ok
        ten.failed_lanes += failed
        if quarantined:
            ten.quarantined_flushes += 1
        ten.breaker.record(ok, failed)
        self._observe(
            "complete", ten.id,
            lanes=ok + failed, failed=failed,
            seconds=time.monotonic() - t0, quarantined=quarantined,
        )
        if not entry.fut.done():
            entry.fut.set_result(res)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Fail queued waiters fast and shut the quarantine coalescers
        (the SHARED coalescer's lifecycle belongs to its owner)."""
        self._closed = True
        for ten in self._tenants.values():
            while ten.queue:
                _, _, entry = heapq.heappop(ten.queue)
                ten.pending_jobs -= 1
                ten.pending_lanes -= entry.lanes
                if not entry.fut.done():
                    entry.fut.set_exception(
                        PlaneOverloadError(ten.id, "closed")
                    )
            if ten.quarantine_coal is not None and hasattr(
                ten.quarantine_coal, "close"
            ):
                ten.quarantine_coal.close()
        if self._task is not None and not self._task.done():
            self._wake.set()
