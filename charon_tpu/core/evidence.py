"""Attributable Byzantine-behaviour evidence (ISSUE 16).

One registry per node collects every Byzantine detection made by the
protocol components (qbft equivocation/floods, forged justifications,
conflicting or spoofed partial signatures), keyed by the offending
peer and an evidence kind. The PR 8 acceptance style applies: evidence
must name ONLY the adversary, so every recording site authenticates
the peer it attributes (message signature or channel identity) before
calling `record`.

The registry feeds two sinks:
  * `app/metrics.py byzantine_hook()` — the `byzantine_evidence_total
    {peer,kind}` counter family, the operator-facing damage ledger;
  * `sigagg` lane exclusion — peers with equivocation-class evidence
    (EXCLUSION_KINDS) are dropped from recombination lanes while enough
    clean partials remain, the per-peer quarantine primitive applied to
    the aggregation path.

Kind strings are shared constants; `core/qbft.py` deliberately emits
the same literals without importing this module (the engine stays
dependency-free — its Definition carries a plain `on_evidence`
callback).
"""

from __future__ import annotations

from typing import Callable, Iterable

# QBFT engine / adapter detections
QBFT_EQUIVOCATION = "qbft_equivocation"  # two msgs in one (type, round) slot
QBFT_FLOOD = "qbft_flood"  # per-sender stored-message bound hit
QBFT_REPLAY = "qbft_replay"  # cross-instance / spoofed-channel delivery
QBFT_MALFORMED = "qbft_malformed"  # structural protocol violation
QBFT_FORGED_JUSTIFICATION = "qbft_forged_justification"  # bad piggybacked sigs

# Partial-signature path detections
PARSIG_CONFLICT = "parsig_conflict"  # double-signed duty/validator
PARSIG_FLOOD = "parsig_flood"  # per-peer pending-set cap hit
PARSIG_INVALID = "parsig_invalid"  # signature verification failed
PARSIG_SPOOF = "parsig_spoof"  # set claiming another peer's share index

# Evidence kinds that prove the peer actively equivocated (not merely
# flooded or delivered garbage): these exclude the peer's lanes from
# sigagg recombination while enough clean partials remain.
EXCLUSION_KINDS = frozenset(
    {QBFT_EQUIVOCATION, PARSIG_CONFLICT, PARSIG_SPOOF}
)

# hook(peer, kind) or hook(peer, kind, detail) — the registry detects
# the arity once at construction (ISSUE 19: the flight recorder wants
# the free-text detail; the metrics counter hook never did, and every
# existing 2-arg hook keeps working unchanged)
EvidenceHook = Callable[..., None]


def _accepts_detail(hook) -> bool:
    import inspect

    try:
        params = list(inspect.signature(hook).parameters.values())
    except (TypeError, ValueError):
        return False
    positional = [
        p
        for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    return len(positional) >= 3


class EvidenceRegistry:
    """Per-node ledger of attributed Byzantine detections.

    `peer` is an opaque identity — the cluster convention is the
    1-based share index everywhere a share index exists (parsig path,
    consensus adapter), and the raw 0-based engine index in pure-qbft
    harnesses. Peers come from authenticated identities, so the key
    space is bounded by the cluster size times the kind catalogue; the
    `max_keys` cap is a defensive backstop, never hit by honest wiring.
    """

    def __init__(
        self, hook: EvidenceHook | None = None, max_keys: int = 4096
    ) -> None:
        self._hook = hook
        self._hook_detail = hook is not None and _accepts_detail(hook)
        self._max_keys = max_keys
        self._counts: dict[tuple[object, str], int] = {}

    def record(self, peer: object, kind: str, detail: str = "") -> None:
        key = (peer, kind)
        n = self._counts.get(key)
        if n is None and len(self._counts) >= self._max_keys:
            return
        self._counts[key] = (n or 0) + 1
        if self._hook is not None:
            if self._hook_detail:
                self._hook(peer, kind, detail)
            else:
                self._hook(peer, kind)

    def count(self, peer: object = None, kind: str | None = None) -> int:
        return sum(
            n
            for (p, k), n in self._counts.items()
            if (peer is None or p == peer) and (kind is None or k == kind)
        )

    def peers(self, kinds: Iterable[str] | None = None) -> set:
        """Peers with any recorded evidence (optionally of given kinds)."""
        wanted = None if kinds is None else set(kinds)
        return {
            p
            for (p, k), n in self._counts.items()
            if n and (wanted is None or k in wanted)
        }

    def excluded_shares(self) -> set:
        """Peers whose lanes sigagg must exclude before recombination."""
        return self.peers(EXCLUSION_KINDS)

    def snapshot(self) -> dict[tuple[object, str], int]:
        return dict(self._counts)
