"""Bcast: submit aggregated signed duties to the beacon node.

Mirrors ref: core/bcast/bcast.go — type-switch per duty kind, broadcast
delay metrics, and duplicate suppression. The beacon client is duck-typed
(beaconmock in tests, the failover multi-client in production).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from charon_tpu.core.eth2data import SignedData
from charon_tpu.core.types import Duty, DutyType, PubKey


@dataclass
class Broadcaster:
    beacon: object
    clock: object | None = None  # SlotClock for delay metrics

    def __post_init__(self) -> None:
        self.broadcast_total: dict[DutyType, int] = {}
        self.broadcast_delay: list[tuple[Duty, float]] = []
        self._registrations: dict[Duty, dict] = {}
        self._subs: list = []  # post-broadcast hooks (inclusion checker)

    def subscribe(self, sub) -> None:
        """Called with (duty, data_set) after a successful broadcast
        (ref: the inclusion checker subscribes downstream of bcast,
        app/app.go:746-780)."""
        self._subs.append(sub)

    async def broadcast(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        """ref: core/bcast/bcast.go:42 Broadcast type-switch."""
        for pubkey, signed in data_set.items():
            if duty.type == DutyType.ATTESTER:
                await self.beacon.submit_attestation(self._with_sig(signed))
            elif duty.type == DutyType.PROPOSER:
                await self.beacon.submit_proposal(signed.payload, signed.signature)
            elif duty.type == DutyType.RANDAO:
                pass  # randao is an input to proposals, never broadcast
            elif duty.type == DutyType.BUILDER_REGISTRATION:
                await self.beacon.submit_registration(signed.payload, signed.signature)
                self._registrations[duty] = data_set  # for the recaster
            elif duty.type == DutyType.EXIT:
                await self.beacon.submit_exit(signed.payload, signed.signature)
            elif duty.type == DutyType.AGGREGATOR:
                await self.beacon.submit_aggregate(signed.payload, signed.signature)
            elif duty.type == DutyType.SYNC_MESSAGE:
                from dataclasses import replace as _replace

                await self.beacon.submit_sync_message(
                    _replace(signed.payload, signature=signed.signature)
                    if hasattr(signed.payload, "signature")
                    else signed.payload
                )
            elif duty.type == DutyType.SYNC_CONTRIBUTION:
                await self.beacon.submit_contribution(signed.payload, signed.signature)
            elif duty.type in (
                DutyType.PREPARE_AGGREGATOR,
                DutyType.PREPARE_SYNC_CONTRIBUTION,
            ):
                pass  # selection proofs are inputs to later duties
            else:
                raise ValueError(f"cannot broadcast duty type {duty.type}")
        self.broadcast_total[duty.type] = (
            self.broadcast_total.get(duty.type, 0) + len(data_set)
        )
        if self.clock is not None:
            self.broadcast_delay.append(
                (duty, time.time() - self.clock.slot_start(duty.slot))
            )
        for sub in self._subs:
            await sub(duty, data_set)

    def _with_sig(self, signed: SignedData):
        """Attestations carry their signature inline."""
        from dataclasses import replace

        return replace(signed.payload, signature=signed.signature)

    async def recast(self, slot) -> None:
        """Re-broadcast validator registrations every epoch
        (ref: core/bcast/recast.go Recaster; wiring app/app.go:677-743).
        Subscribe to scheduler slots."""
        if slot.slot % slot.slots_per_epoch != 0:
            return
        for duty, data_set in list(self._registrations.items()):
            for pubkey, signed in data_set.items():
                await self.beacon.submit_registration(
                    signed.payload, signed.signature
                )
