"""Bcast: submit aggregated signed duties to the beacon node.

Mirrors ref: core/bcast/bcast.go — type-switch per duty kind, broadcast
delay metrics, and duplicate suppression. The beacon client is duck-typed
(beaconmock in tests, the failover multi-client in production).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from charon_tpu.core.eth2data import SignedData
from charon_tpu.core.types import Duty, DutyType, PubKey


@dataclass
class Broadcaster:
    beacon: object
    clock: object | None = None  # SlotClock for delay metrics

    def __post_init__(self) -> None:
        self.broadcast_total: dict[DutyType, int] = {}
        self.broadcast_delay: list[tuple[Duty, float]] = []
        self.recast_errors = 0  # feeds app/health (ref: recast.go metric)
        self.retried_total = 0  # deadline-aware submit retries
        self._registrations: dict[Duty, dict] = {}
        self._subs: list = []  # post-broadcast hooks (inclusion checker)

    def subscribe(self, sub) -> None:
        """Called with (duty, data_set) after a successful broadcast
        (ref: the inclusion checker subscribes downstream of bcast,
        app/app.go:746-780)."""
        self._subs.append(sub)

    async def _submit(self, duty: Duty, fn, *args) -> None:
        """Submit with deadline-aware retry: a transient BN failure
        (connection reset, timeout, every-endpoint-down) retries with
        jittered exponential backoff (app/expbackoff FAST schedule)
        until the duty's deadline — a flapping BN a few hundred ms
        before recovery must not turn an aggregated signature into a
        missed duty. Without a clock (bare unit-test wiring) the first
        error propagates unchanged."""
        import asyncio

        from charon_tpu.app.expbackoff import FAST_CONFIG, backoff_delay
        from charon_tpu.app.retry import retryable_errors

        attempt = 0
        # wall duty deadline anchored to monotonic ONCE, at entry while
        # the clock is still honest (the PR 8 _arm bug class): a host
        # clock step mid-retry must neither abort the remaining window
        # nor retry past the duty deadline
        deadline_mono = (
            None
            if self.clock is None
            else time.monotonic()
            + (self.clock.duty_deadline(duty) - time.time())  # lint: allow(monotonic-clock) — one-shot wall->mono anchor
        )
        while True:
            try:
                return await fn(*args)
            except retryable_errors() as e:
                if deadline_mono is None:
                    raise
                delay = backoff_delay(FAST_CONFIG, attempt)
                if time.monotonic() + delay >= deadline_mono:
                    raise
                if attempt == 0:
                    from charon_tpu.app import log

                    log.warn(
                        "broadcast failed; retrying until duty deadline",
                        topic="bcast",
                        duty=str(duty),
                        err=f"{type(e).__name__}: {e}",
                    )
                self.retried_total += 1
                attempt += 1
                await asyncio.sleep(delay)

    async def broadcast(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        """ref: core/bcast/bcast.go:42 Broadcast type-switch."""
        for pubkey, signed in data_set.items():
            if duty.type == DutyType.ATTESTER:
                await self._submit(
                    duty, self.beacon.submit_attestation, self._with_sig(signed)
                )
            elif duty.type == DutyType.PROPOSER:
                await self._submit(
                    duty, self.beacon.submit_proposal, signed.payload, signed.signature
                )
            elif duty.type == DutyType.RANDAO:
                pass  # randao is an input to proposals, never broadcast
            elif duty.type == DutyType.BUILDER_REGISTRATION:
                await self._submit(
                    duty, self.beacon.submit_registration, signed.payload, signed.signature
                )
                # merge per pubkey — separate submissions share the duty
                # key (slot 0), and the recaster needs all of them
                merged = dict(self._registrations.get(duty, {}))
                merged.update(data_set)
                self._registrations[duty] = merged
            elif duty.type == DutyType.EXIT:
                await self._submit(
                    duty, self.beacon.submit_exit, signed.payload, signed.signature
                )
            elif duty.type == DutyType.AGGREGATOR:
                await self._submit(
                    duty, self.beacon.submit_aggregate, signed.payload, signed.signature
                )
            elif duty.type == DutyType.SYNC_MESSAGE:
                from dataclasses import replace as _replace

                await self._submit(
                    duty,
                    self.beacon.submit_sync_message,
                    _replace(signed.payload, signature=signed.signature)
                    if hasattr(signed.payload, "signature")
                    else signed.payload,
                )
            elif duty.type == DutyType.SYNC_CONTRIBUTION:
                await self._submit(
                    duty, self.beacon.submit_contribution, signed.payload, signed.signature
                )
            elif duty.type in (
                DutyType.PREPARE_AGGREGATOR,
                DutyType.PREPARE_SYNC_CONTRIBUTION,
            ):
                pass  # selection proofs are inputs to later duties
            else:
                raise ValueError(f"cannot broadcast duty type {duty.type}")
        self.broadcast_total[duty.type] = (
            self.broadcast_total.get(duty.type, 0) + len(data_set)
        )
        if self.clock is not None:
            self.broadcast_delay.append(
                # attribution edge: delay INTO the slot — both terms live
                # on the wall timeline (slots are wall-clock)
                (duty, time.time() - self.clock.slot_start(duty.slot))  # lint: allow(monotonic-clock)
            )
        for sub in self._subs:
            # post-broadcast observers (inclusion checker) are
            # best-effort: the duty IS broadcast by now, and an observer
            # bug must not re-report it failed — nor cascade the error
            # back through the aggregation chain that invoked us
            try:
                await sub(duty, data_set)
            except Exception as e:  # noqa: BLE001
                from charon_tpu.app import log

                log.warn(
                    "post-broadcast subscriber failed",
                    topic="bcast",
                    duty=str(duty),
                    err=f"{type(e).__name__}: {e}",
                )

    def _with_sig(self, signed: SignedData):
        """Attestations carry their signature inline."""
        from dataclasses import replace

        return replace(signed.payload, signature=signed.signature)

    def load_pregen_registrations(self, validators) -> int:
        """Load the lock file's pre-generated builder registrations so the
        recaster re-broadcasts them even when no VC ever submits one
        (ref: core/bcast/recast.go pre-generate path — lock-file
        registrations signed during the DKG, dkg.go:190-194).

        `validators`: the lock's DistributedValidator entries. Returns the
        number loaded."""
        from charon_tpu.eth2util import registration as regmod

        loaded = 0
        self._pregen: list[tuple[object, bytes]] = []
        for dv in validators:
            obj = getattr(dv, "builder_registration", None) or {}
            if not obj.get("message"):
                continue
            reg, sig = regmod.from_lock_json(obj)
            self._pregen.append((reg, sig))
            loaded += 1
        return loaded

    async def recast(self, slot) -> None:
        """Re-broadcast validator registrations every epoch
        (ref: core/bcast/recast.go Recaster; wiring app/app.go:677-743).
        Subscribe to scheduler slots.

        Failures are contained: the scheduler's slot loop has no
        exception isolation, and a transient BN outage at an epoch
        boundary must not kill duty scheduling (the reference's recaster
        logs and carries on)."""
        if slot.slot % slot.slots_per_epoch != 0:
            return
        from charon_tpu.app import log

        async def _submit_one(pubkey, payload, signature) -> None:
            # per-registration isolation: one persistently rejected
            # registration (e.g. a 400 on one pubkey) must not starve
            # every other validator's recast
            try:
                await self.beacon.submit_registration(payload, signature)
            except Exception as e:  # noqa: BLE001 — log-and-continue
                self.recast_errors += 1  # feeds app/health recast check
                log.warn(
                    "registration recast failed",
                    topic="bcast",
                    slot=slot.slot,
                    pubkey=str(pubkey)[:18],
                    err=str(e),
                )

        for duty, data_set in list(self._registrations.items()):
            for pubkey, signed in data_set.items():
                await _submit_one(pubkey, signed.payload, signed.signature)
        # pre-generated registrations from the lock: skip any pubkey
        # the VC has submitted a fresher registration for
        submitted = {
            getattr(signed.payload, "pubkey", None)
            for ds in self._registrations.values()
            for signed in ds.values()
        }
        for reg, sig in getattr(self, "_pregen", []):
            if reg.pubkey in submitted:
                continue
            await _submit_one(reg.pubkey, reg, sig)
