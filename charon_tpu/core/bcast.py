"""Bcast: submit aggregated signed duties to the beacon node.

Mirrors ref: core/bcast/bcast.go — type-switch per duty kind, broadcast
delay metrics, and duplicate suppression. The beacon client is duck-typed
(beaconmock in tests, the failover multi-client in production).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from charon_tpu.core.eth2data import SignedData
from charon_tpu.core.types import Duty, DutyType, PubKey


@dataclass
class Broadcaster:
    beacon: object
    clock: object | None = None  # SlotClock for delay metrics

    def __post_init__(self) -> None:
        self.broadcast_total: dict[DutyType, int] = {}
        self.broadcast_delay: list[tuple[Duty, float]] = []

    async def broadcast(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        """ref: core/bcast/bcast.go:42 Broadcast type-switch."""
        for pubkey, signed in data_set.items():
            if duty.type == DutyType.ATTESTER:
                await self.beacon.submit_attestation(self._with_sig(signed))
            elif duty.type == DutyType.PROPOSER:
                await self.beacon.submit_proposal(signed.payload, signed.signature)
            elif duty.type == DutyType.RANDAO:
                pass  # randao is an input to proposals, never broadcast
            elif duty.type == DutyType.BUILDER_REGISTRATION:
                await self.beacon.submit_registration(signed.payload, signed.signature)
            elif duty.type == DutyType.EXIT:
                await self.beacon.submit_exit(signed.payload, signed.signature)
            else:
                raise ValueError(f"cannot broadcast duty type {duty.type}")
        self.broadcast_total[duty.type] = (
            self.broadcast_total.get(duty.type, 0) + len(data_set)
        )
        if self.clock is not None:
            self.broadcast_delay.append(
                (duty, time.time() - self.clock.slot_start(duty.slot))
            )

    def _with_sig(self, signed: SignedData):
        """Attestations carry their signature inline."""
        from dataclasses import replace

        return replace(signed.payload, signature=signed.signature)
