"""Scheduler: slot ticker + per-epoch duty resolution + offset triggers.

Mirrors ref: core/scheduler/scheduler.go — ticks slots from genesis time
and slot duration (scheduler.go:546-548), resolves attester/proposer/sync
duties per epoch from the beacon node (scheduler.go:246), triggers each
duty at its offset into the slot (attester ⅓, aggregator ⅔ —
core/scheduler/offset.go:12-16), and emits slot events to subscribers
(fee-recipient, validator-cache refresh, infosync — ref app/app.go:433+).

asyncio redesign: one ticker task; each duty trigger is its own task (the
reference's goroutine-per-duty, scheduler.go:193). Deterministic tests
inject a fake clock/sleep (the reference injects clockwork + delayFunc,
scheduler.go:27-43).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from charon_tpu.core.deadline import SlotClock
from charon_tpu.core.types import Duty, DutyType, PubKey

# Trigger offsets as fractions of the slot (ref: core/scheduler/offset.go).
OFFSETS = {
    DutyType.ATTESTER: 1 / 3,
    DutyType.AGGREGATOR: 2 / 3,
    DutyType.SYNC_CONTRIBUTION: 2 / 3,
    DutyType.PROPOSER: 0.0,
    DutyType.RANDAO: 0.0,
    DutyType.SYNC_MESSAGE: 1 / 3,
}


@dataclass(frozen=True)
class Slot:
    slot: int
    time: float
    slot_duration: float
    slots_per_epoch: int

    @property
    def epoch(self) -> int:
        return self.slot // self.slots_per_epoch

    def is_last_in_epoch(self) -> bool:
        return self.slot % self.slots_per_epoch == self.slots_per_epoch - 1


@dataclass(frozen=True)
class DutyDefinition:
    """What the VC needs to perform a duty (ref: core/types.go
    DutyDefinition — attester definitions carry committee coordinates)."""

    pubkey: PubKey
    validator_index: int
    committee_index: int = 0
    committee_length: int = 1
    committees_at_slot: int = 1
    validator_committee_index: int = 0
    # sync-committee duties: the validator's full set of committee
    # positions (0..511). The workflow currently drives the FIRST
    # position's subcommittee (committee_index/validator_committee_index
    # derive from it); the rest are carried for forward-compat and the
    # scheduler logs when a validator holds more than one seat.
    sync_committee_positions: tuple = ()


DutiesSub = Callable[[Duty, dict[PubKey, DutyDefinition]], Awaitable[None]]
SlotSub = Callable[[Slot], Awaitable[None]]


class Scheduler:
    """beacon: duck-typed beacon client (testutil/beaconmock or the real
    multi-client); validators: pubkey -> validator index map."""

    def __init__(
        self,
        beacon,
        clock: SlotClock,
        validators: dict[PubKey, int],
        slots_per_epoch: int = 32,
        # wall clock by design: the slot ticker follows the chain's
        # wall-clock schedule (genesis arithmetic) — skew tests inject
        now: Callable[[], float] = time.time,  # lint: allow(monotonic-clock)
        sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
    ) -> None:
        self.beacon = beacon
        self.clock = clock
        self.validators = dict(validators)
        self.slots_per_epoch = slots_per_epoch
        self._now = now
        self._sleep = sleep
        self._duty_subs: list[DutiesSub] = []
        self._slot_subs: list[SlotSub] = []
        # epoch -> duty -> pubkey -> definition
        self._defs: dict[int, dict[Duty, dict[PubKey, DutyDefinition]]] = {}
        self._stop = asyncio.Event()
        self._tasks: set[asyncio.Task] = set()

    def subscribe_duties(self, sub: DutiesSub) -> None:
        self._duty_subs.append(sub)

    def subscribe_slots(self, sub: SlotSub) -> None:
        self._slot_subs.append(sub)

    def get_duty_definition(self, duty: Duty) -> dict[PubKey, DutyDefinition]:
        """ref: core/scheduler/scheduler.go:142 GetDutyDefinition."""
        epoch = duty.slot // self.slots_per_epoch
        return dict(self._defs.get(epoch, {}).get(duty, {}))

    # -- lifecycle --------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        for t in self._tasks:
            t.cancel()

    def reset(self) -> None:
        """Clear a previous stop() so run() can be re-entered — the
        RESTART boundary owns this, not run() itself: clearing inside
        run() would erase a stop() issued between task creation and the
        task's first execution, leaving the node unstoppable."""
        self._stop.clear()

    async def run(self) -> None:
        """Tick slots until stopped (ref: scheduler.go:97 Run). Waits for
        beacon sync first, retrying single-shot probes (ref:
        scheduler.go:678 waitBeaconSync).

        Re-runnable after stop() + reset(): a crashed-then-restarted
        node calls run() again on the same wired components
        (crash/recover scenarios; ref: charon's crash-only model
        restarts the whole wiring, the asyncio analogue restarts the
        tick loop)."""
        while not self._stop.is_set():
            try:
                await self.beacon.await_synced()
                break
            except Exception:
                await asyncio.sleep(5)
        while not self._stop.is_set():
            now = self._now()
            slot_no = self.clock.slot_at(now)
            start = self.clock.slot_start(slot_no)
            if start + self.clock.slot_duration <= now:
                slot_no += 1
                start = self.clock.slot_start(slot_no)
            if start > now:
                await self._sleep(start - now)
            if self._stop.is_set():
                return
            await self._handle_slot(
                Slot(
                    slot=slot_no,
                    time=start,
                    slot_duration=self.clock.slot_duration,
                    slots_per_epoch=self.slots_per_epoch,
                )
            )
            # sleep to next slot start
            next_start = self.clock.slot_start(slot_no + 1)
            delta = next_start - self._now()
            if delta > 0:
                await self._sleep(delta)

    async def _handle_slot(self, slot: Slot) -> None:
        for sub in self._slot_subs:
            # slot observers (inclusion checker, infosync, recaster) are
            # isolated: one observer hitting a flaky BN must not kill
            # the duty tick loop for every remaining slot
            try:
                await sub(slot)
            except Exception as e:  # noqa: BLE001
                from charon_tpu.app import log

                log.warn(
                    "slot subscriber failed",
                    topic="scheduler",
                    slot=slot.slot,
                    err=f"{type(e).__name__}: {e}",
                )
        try:
            await self._resolve_epoch(slot.epoch)
        except Exception as e:  # noqa: BLE001 — degraded: retry next slot
            from charon_tpu.app import log

            log.warn(
                "epoch duty resolution failed; retrying next slot",
                topic="scheduler",
                epoch=slot.epoch,
                err=f"{type(e).__name__}: {e}",
            )
        duties = self._defs.get(slot.epoch, {})
        for duty, defs in duties.items():
            if duty.slot != slot.slot:
                continue
            self._spawn(self._trigger(slot, duty, defs))

    def _spawn(self, coro) -> None:
        task = asyncio.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _trigger(self, slot: Slot, duty: Duty, defs) -> None:
        """Goroutine-per-duty analogue (ref: scheduler.go:172-214): wait to
        the duty's offset into the slot, then emit."""
        offset = OFFSETS.get(duty.type, 0.0) * slot.slot_duration
        delay = slot.time + offset - self._now()
        if delay > 0:
            await self._sleep(delay)
        for sub in self._duty_subs:
            await sub(duty, dict(defs))

    async def _resolve_epoch(self, epoch: int) -> None:
        """Fetch duty definitions for the epoch once (ref: scheduler.go:246
        resolveDuties)."""
        if epoch in self._defs:
            return
        out: dict[Duty, dict[PubKey, DutyDefinition]] = {}
        att = await self.beacon.attester_duties(epoch, self.validators)
        for ad in att:
            duty = Duty(ad["slot"], DutyType.ATTESTER)
            out.setdefault(duty, {})[ad["pubkey"]] = DutyDefinition(
                pubkey=ad["pubkey"],
                validator_index=ad["validator_index"],
                committee_index=ad["committee_index"],
                committee_length=ad["committee_length"],
                committees_at_slot=ad["committees_at_slot"],
                validator_committee_index=ad["validator_committee_index"],
            )
        prop = await self.beacon.proposer_duties(epoch, self.validators)
        for pd in prop:
            duty = Duty(pd["slot"], DutyType.PROPOSER)
            out.setdefault(duty, {})[pd["pubkey"]] = DutyDefinition(
                pubkey=pd["pubkey"],
                validator_index=pd["validator_index"],
            )
        # Aggregator duties mirror attester duties at the ⅔-slot offset —
        # every attester is a potential aggregator; actual selection is
        # decided by the aggregated selection proof (ref: scheduler
        # resolveAttDuties also schedules DutyAggregator,
        # core/scheduler/scheduler.go:246+).
        for duty, defs in [
            (d, v) for d, v in out.items() if d.type == DutyType.ATTESTER
        ]:
            out[Duty(duty.slot, DutyType.AGGREGATOR)] = dict(defs)
        # Sync-committee membership spans the epoch: one SYNC_MESSAGE and
        # one SYNC_CONTRIBUTION duty per slot for each member
        # (ref: scheduler.go resolveSyncCommDuties).
        if hasattr(self.beacon, "sync_duties"):
            sync = await self.beacon.sync_duties(epoch, self.validators)
            for slot in range(
                epoch * self.slots_per_epoch, (epoch + 1) * self.slots_per_epoch
            ):
                for sd in sync:
                    # membership is a committee POSITION (0..511); the
                    # subcommittee and the bit inside it derive from it
                    # (spec duty shape: validator_sync_committee_indices)
                    positions = [
                        int(p)
                        for p in sd.get("sync_committee_indices", [])
                    ] or [int(sd.get("subcommittee_index", 0)) * 128]
                    if len(positions) > 1 and slot % self.slots_per_epoch == 0:
                        from charon_tpu.app import log

                        log.warn(
                            "validator holds multiple sync-committee "
                            "seats; only the first position's "
                            "subcommittee is driven",
                            topic="sched",
                            validator=sd["validator_index"],
                            positions=positions,
                        )
                    d = DutyDefinition(
                        pubkey=sd["pubkey"],
                        validator_index=sd["validator_index"],
                        committee_index=positions[0] // 128,
                        validator_committee_index=positions[0] % 128,
                        sync_committee_positions=tuple(positions),
                    )
                    out.setdefault(
                        Duty(slot, DutyType.SYNC_MESSAGE), {}
                    )[sd["pubkey"]] = d
                    out.setdefault(
                        Duty(slot, DutyType.SYNC_CONTRIBUTION), {}
                    )[sd["pubkey"]] = d
        self._defs[epoch] = out
        # keep two epochs of definitions
        for old in [e for e in self._defs if e < epoch - 1]:
            del self._defs[old]
