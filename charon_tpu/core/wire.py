"""wire(): stitch the core workflow components into the duty pipeline.

Mirrors ref: core/interfaces.go:282-357 core.Wire — a pure subscription
graph with optional wrapping (tracing, tracking, async-retry) applied to
every edge. Components are duck-typed; any may be replaced by a test fake
(the reference proves this pattern with its simnet, ref: app/app.go:862).

Subscription graph (ref: core/interfaces.go:336-356):

    scheduler --duties--> fetcher --proposals--> consensus --decided--> dutydb
    validatorapi --partials--> parsigdb --internal--> parsigex --> peers
    parsigdb --threshold--> sigagg --> aggsigdb
                                  \\--> broadcaster
"""

from __future__ import annotations

from typing import Awaitable, Callable, Sequence

WireOption = Callable[[str, Callable], Callable]


def tracing(tracer=None) -> WireOption:
    """wire() option (sibling of app/metrics.instrument and
    core/tracker.tracking): every subscription edge runs inside a span
    rooted at the DETERMINISTIC duty trace id (app/tracer.duty_trace_id),
    so Scheduler→Fetcher→Consensus→DutyDB→ValidatorAPI→ParSigDB→ParSigEx
    →SigAgg→AggSigDB→Broadcaster each contribute one nested span per
    duty, and spans recorded on different nodes merge into one
    cross-node trace (ref: core/tracing.go + core.WithTracing,
    app/app.go:569). Attrs: duty, slot, duty type, and the pubkey count
    of dict-shaped payloads (duty-set fan-in width)."""

    def option(name: str, fn: Callable) -> Callable:
        async def wrapped(duty, *args, **kwargs):
            # lazy: core must not import app at module load
            from charon_tpu.app.tracer import span

            attrs = {"duty_type": str(getattr(duty, "type", ""))}
            if args and hasattr(args[0], "keys"):
                attrs["pubkeys"] = len(args[0])
            with span(name, duty=duty, tracer=tracer, **attrs):
                return await fn(duty, *args, **kwargs)

        return wrapped

    return option


def wire(
    *,
    scheduler,
    fetcher,
    consensus,
    dutydb,
    validatorapi,
    parsigdb,
    parsigex,
    sigagg,
    aggsigdb,
    broadcaster,
    options: Sequence[WireOption] = (),
) -> None:
    def wrap(name: str, fn: Callable) -> Callable:
        for opt in options:
            fn = opt(name, fn)
        return fn

    scheduler.subscribe_duties(wrap("fetcher.fetch", fetcher.fetch))
    fetcher.register_consensus(wrap("consensus.propose", consensus.propose))
    fetcher.register_agg_sig_db(wrap("aggsigdb.await", aggsigdb.await_))
    fetcher.register_await_attestation(
        wrap("dutydb.await_attestation", dutydb.await_attestation)
    )
    consensus.subscribe(wrap("dutydb.store", dutydb.store))
    validatorapi.register_await_attestation(dutydb.await_attestation)
    validatorapi.register_await_proposal(dutydb.await_proposal)
    validatorapi.register_await_aggregated_attestation(
        dutydb.await_aggregated_attestation
    )
    validatorapi.register_await_sync_contribution(
        dutydb.await_sync_contribution
    )
    validatorapi.register_await_sync_message(dutydb.await_sync_message)
    validatorapi.register_pubkey_by_attestation(dutydb.pubkey_by_attestation)
    validatorapi.register_await_aggregated(aggsigdb.await_)
    validatorapi.register_get_duty_definition(scheduler.get_duty_definition)
    validatorapi.subscribe(wrap("parsigdb.store_internal", parsigdb.store_internal))
    parsigdb.subscribe_internal(wrap("parsigex.broadcast", parsigex.broadcast))
    parsigex.subscribe(wrap("parsigdb.store_external", parsigdb.store_external))
    parsigdb.subscribe_threshold(wrap("sigagg.aggregate", sigagg.aggregate))
    sigagg.subscribe(wrap("aggsigdb.store", aggsigdb.store_set))
    sigagg.subscribe(wrap("broadcaster.broadcast", broadcaster.broadcast))
