"""QBFT: a pure, transport-agnostic implementation of the Istanbul BFT
consensus algorithm (Moniz, arXiv:2002.03613).

Plays the role of ref: core/qbft/qbft.go — a generic engine with zero
dependencies, driven entirely through a Definition (validation, leader
selection, timers) and a Transport (broadcast + inbound queue), so the
simnet runs it over in-memory channels and production over the p2p layer.
This is a from-scratch implementation of the published algorithm, asyncio
style: one `run` coroutine per consensus instance.

Quorum: ceil(2n/3); tolerates floor((n-1)/3) byzantine nodes.

The subtle parts, implemented per the paper:
  * PRE-PREPARE justification for round > 1 (a quorum of ROUND-CHANGEs,
    and the proposed value must match the highest prepared value among
    them, which itself must be justified by a PREPARE quorum);
  * ROUND-CHANGE carries (prepared_round, prepared_value) plus the
    PREPARE messages justifying them;
  * f+1 ROUND-CHANGEs ahead of us pull us into the smallest such round.
"""

from __future__ import annotations

import asyncio
import enum
import math
from dataclasses import dataclass, field, replace
from typing import Awaitable, Callable, Hashable, Sequence


class MsgType(enum.IntEnum):
    PRE_PREPARE = 1
    PREPARE = 2
    COMMIT = 3
    ROUND_CHANGE = 4


@dataclass(frozen=True)
class Msg:
    """One QBFT message. `value` is the proposed value (hashable; the
    adapter layer uses 32-byte hashes with values carried out-of-band, ref:
    core/consensus/qbft/transport.go values-by-hash). Justification carries
    piggybacked messages for PRE-PREPARE/ROUND-CHANGE rules.

    `signature` authenticates the message independently of the channel it
    arrived on (ref: core/consensus/qbft/transport.go:25-50 signs every
    msg; qbft.go:561 verifies) — required because justification messages
    are relayed by third parties, so channel auth alone cannot vouch for
    their claimed sources. The engine treats it as opaque; signing happens
    via Definition.sign_msg and verification via Definition.is_valid."""

    type: MsgType
    instance: Hashable
    source: int  # node index 0..n-1
    round: int
    value: Hashable | None = None
    prepared_round: int = 0
    prepared_value: Hashable | None = None
    justification: tuple["Msg", ...] = ()
    signature: bytes = b""


def msg_digest(msg: Msg) -> bytes:
    """Deterministic 32-byte digest of a message, excluding its signature.

    Justification messages contribute their own digests *and* signatures,
    binding the exact set of piggybacked (already-signed) messages to the
    outer signature."""
    import hashlib

    just = tuple(
        (msg_digest(j), j.signature) for j in msg.justification
    )
    material = repr(
        (
            int(msg.type),
            msg.instance,
            msg.source,
            msg.round,
            msg.value,
            msg.prepared_round,
            msg.prepared_value,
            just,
        )
    ).encode()
    return hashlib.sha256(material).digest()


# Round-timer strategies (ref: core/consensus/utils/roundtimer.go:17-19
# constants, :72-97 increasing, :99-152 eager-double-linear). A timer is
# instantiated PER INSTANCE (ref TimerFunc is per duty) because the
# double-eager variant is stateful across restarts within one instance.
INC_ROUND_START = 0.75
INC_ROUND_INCREASE = 0.25
LINEAR_ROUND_INC = 1.0


class IncreasingRoundTimer:
    """Fresh `start + inc*round` countdown on every (re)arm — a restart
    for the same round fully resets it."""

    type = "inc"

    def __init__(
        self,
        start: float = INC_ROUND_START,
        increase: float = INC_ROUND_INCREASE,
    ) -> None:
        self._start = start
        self._increase = increase

    def duration(self, rnd: int, now: float) -> float:
        return self._start + self._increase * rnd


class DoubleEagerLinearRoundTimer:
    """Linear `round * inc` timeout whose per-round deadline is ABSOLUTE:
    re-arming the same round (the justified-pre-prepare restart) extends
    the deadline to first_deadline + linear(round) — i.e. doubles the
    round instead of resetting it, keeping every peer's round end-time
    aligned with the round start rather than with when each peer happened
    to see the leader's pre-prepare
    (ref: core/consensus/utils/roundtimer.go:112-131 rationale)."""

    type = "eager_dlinear"

    def __init__(self, inc: float = LINEAR_ROUND_INC) -> None:
        self._inc = inc
        self._first: dict[int, float] = {}

    def duration(self, rnd: int, now: float) -> float:
        first = self._first.get(rnd)
        if first is None:
            deadline = now + self._inc * rnd
            self._first[rnd] = deadline
        else:
            deadline = first + self._inc * rnd
        return max(0.0, deadline - now)


class _FnTimer:
    """Adapter for the legacy `Definition.timeout` callable."""

    type = "inc"

    def __init__(self, fn: Callable[[int], float]) -> None:
        self._fn = fn

    def duration(self, rnd: int, now: float) -> float:
        return self._fn(rnd)


@dataclass
class Definition:
    """Parameters binding the pure engine to an environment."""

    nodes: int
    leader: Callable[[Hashable, int], int]  # (instance, round) -> node idx
    # round -> timeout seconds (ref-equivalent default: 0.75 + 0.25*round)
    timeout: Callable[[int], float] = lambda r: 0.75 + 0.25 * r
    # Per-instance round-timer factory; when set it takes precedence over
    # `timeout` (ref: qbft.go:36 Definition.NewTimer from TimerFunc).
    new_timer: Callable[[], object] | None = None
    # Authenticates a message (signature over msg_digest against the
    # per-index cluster key) AND, for messages carrying justifications,
    # each piggybacked message (ref: qbft.go:561 verifies wrapped msgs).
    is_valid: Callable[[Msg], bool] = lambda m: True
    # Applied to every outbound message before broadcast/loopback.
    sign_msg: Callable[[Msg], Msg] = lambda m: m
    # Outer-signature-only check (no justification recursion). Used to
    # attribute evidence safely: a message whose SENDER authenticates but
    # whose piggybacked justification does not was forged by that sender,
    # while a message failing the outer check proves nothing about the
    # claimed source. None = fall back to is_valid (the unsigned/
    # channel-authenticated fabrics, where is_valid is trivially cheap).
    verify_sender: Callable[[Msg], bool] | None = None
    # Per-sender cap on messages the engine STORES for this instance (the
    # Transport bound only covers outstanding inbox depth — a sustained
    # flood streams through it into `_Engine.msgs` otherwise). A
    # protocol-honest sender stores <= 4 messages per round, so the
    # default allows ~32 rounds of headroom.
    max_stored_per_source: int = 128
    # Byzantine-evidence sink: (source, kind) per attributed detection.
    # Kind literals match core/evidence.py constants; the engine stays
    # import-free by design.
    on_evidence: Callable[[int, str], None] | None = None

    @property
    def quorum(self) -> int:
        return math.ceil(2 * self.nodes / 3)

    @property
    def faulty(self) -> int:
        return (self.nodes - 1) // 3


class DropReason(enum.Enum):
    """Why a transport refused an inbound message (typed, countable)."""

    SOURCE_OVER_BOUND = "source_over_bound"


class Transport:
    """Broadcast + inbound queue. The engine owns no sockets.

    The inbox is bounded per source (ref: core/qbft bounds the per-peer
    FIFO) so one byzantine peer cannot grow memory without limit: messages
    beyond `max_buffered_per_source` outstanding from one source are
    dropped at receive time, with the drop typed and counted in `drops`
    so callers (and the Byzantine harness) can assert the bound fired."""

    def __init__(
        self,
        broadcast: Callable[[Msg], Awaitable[None]],
        max_buffered_per_source: int = 128,
    ):
        self.broadcast = broadcast
        self.inbox: asyncio.Queue[Msg] = asyncio.Queue()
        self.max_buffered_per_source = max_buffered_per_source
        self._buffered: dict[int, int] = {}
        # (source, DropReason) -> count of refused messages
        self.drops: dict[tuple[int, DropReason], int] = {}

    def receive(self, msg: Msg) -> bool:
        """Enqueue an inbound message; False = dropped (source over bound)."""
        n = self._buffered.get(msg.source, 0)
        if n >= self.max_buffered_per_source:
            key = (msg.source, DropReason.SOURCE_OVER_BOUND)
            self.drops[key] = self.drops.get(key, 0) + 1
            return False
        self._buffered[msg.source] = n + 1
        self.inbox.put_nowait(msg)
        return True

    def _consumed(self, msg: Msg) -> None:
        n = self._buffered.get(msg.source, 0)
        if n > 0:
            self._buffered[msg.source] = n - 1


async def run(
    defn: Definition,
    transport: Transport,
    instance: Hashable,
    node: int,
    value: Hashable | None,
    value_ch: asyncio.Future | None = None,
    stats: dict | None = None,
) -> Hashable:
    """Run one QBFT instance until it decides; returns the decided value.

    `value` is this node's proposal input (may be None initially with a
    `value_ch` future supplying it later — the participate-then-propose
    pattern, ref: core/consensus/qbft/qbft.go Propose vs Participate).

    `stats`, when given, receives `{"round": decided_round}` on decide —
    the adapter feeds it into the decided-rounds metric (ref:
    consensus metrics SetDecidedRounds per timer type)."""
    engine = _Engine(defn, transport, instance, node)
    result = await engine.run(value, value_ch)
    if stats is not None:
        stats["round"] = engine.round
        stats["drops"] = engine.drop_stats()
    return result


class _Engine:
    def __init__(self, defn: Definition, transport: Transport, instance, node: int):
        self.d = defn
        self.t = transport
        self.instance = instance
        self.node = node
        self.round = 1
        self.prepared_round = 0
        self.prepared_value = None
        self.prepare_quorum_just: tuple[Msg, ...] = ()
        self.input_value = None
        # dedup: (type, source, round) -> Msg (first wins per slot)
        self.msgs: dict[tuple[MsgType, int, int], Msg] = {}
        # stored-message count per source (bounded by
        # Definition.max_stored_per_source — see _accept)
        self._stored_per_source: dict[int, int] = {}
        # flood evidence is attributed at most once per source per
        # instance (attribution costs one outer signature verify; the
        # drop itself stays free)
        self._flood_flagged: set[int] = set()
        # typed drop counters (satellite: dropped AND counted)
        self.replay_dropped = 0  # foreign-instance messages
        self.dup_dropped = 0  # identical re-deliveries
        self.flood_dropped = 0  # per-source stored bound hit
        self.equivocation_dropped = 0  # conflicting msg in a filled slot
        self.sent_prepare: set[int] = set()
        self.sent_commit: set[int] = set()
        self.sent_preprepare: set[int] = set()
        self.sent_round_change: set[int] = set()
        self.decided: asyncio.Future = None  # type: ignore
        self._restart_timer = None  # bound in run()
        self._timer_round = 0  # round the live timer is armed for

    # -- helpers ----------------------------------------------------------

    def _collect(self, typ: MsgType, rnd: int) -> list[Msg]:
        return [
            m
            for (t, _, r), m in self.msgs.items()
            if t == typ and r == rnd
        ]

    def _quorum_value(self, typ: MsgType, rnd: int) -> Hashable | None:
        """Value (or hash) agreed by a quorum of messages of typ@rnd."""
        counts: dict = {}
        for m in self._collect(typ, rnd):
            counts[m.value] = counts.get(m.value, 0) + 1
            if counts[m.value] >= self.d.quorum:
                return m.value
        return None

    async def _send(self, msg: Msg) -> None:
        msg = self.d.sign_msg(msg)
        await self.t.broadcast(msg)
        # Loopback: our own message must also drive the upon-rules (it may
        # be the final piece of a quorum). Recursion is bounded by the
        # sent_* dedup sets.
        if self._accept(msg):
            await self._on_msg(msg)

    def drop_stats(self) -> dict[str, int]:
        """Typed drop counters (surfaced via qbft.run stats)."""
        return {
            "replay": self.replay_dropped,
            "duplicate": self.dup_dropped,
            "flood": self.flood_dropped,
            "equivocation": self.equivocation_dropped,
        }

    def _evidence(self, source: int, kind: str) -> None:
        if self.d.on_evidence is not None:
            self.d.on_evidence(source, kind)

    def _sender_authentic(self, msg: Msg) -> bool:
        """May evidence be attributed to msg.source? Outer signature only
        — without this check, garbage stamped with a victim's source
        index would let an adversary frame an honest peer."""
        if self.d.verify_sender is not None:
            return self.d.verify_sender(msg)
        return self.d.is_valid(msg)

    def _accept(self, msg: Msg) -> bool:
        if msg.instance != self.instance:
            # Cross-instance replay: counted but NOT attributed here —
            # msg.source names the original (possibly honest) signer,
            # not whoever replayed the frame. Channel-level attribution
            # lives in the adapter (consensus_qbft.deliver sender check).
            self.replay_dropped += 1
            return False
        if not (0 <= msg.source < self.d.nodes):
            return False
        # Dedup BEFORE signature verification: replaying an already-stored
        # message must not cost ECDSA verifies (a justification-laden msg
        # carries ~2*quorum signatures — free CPU amplification otherwise).
        key = (msg.type, msg.source, msg.round)
        stored = self.msgs.get(key)
        if stored is not None:
            if msg == stored or msg_digest(msg) == msg_digest(stored):
                self.dup_dropped += 1  # identical content: plain replay
            elif len(msg.justification) <= 2 * self.d.nodes and (
                self.d.is_valid(msg)
            ):
                # Two DIFFERENT validly-signed messages in one
                # (type, source, round) slot: equivocation. First wins;
                # the full is_valid (not just the outer check) runs first
                # so unverifiable garbage cannot frame the slot's owner —
                # one verify per colliding frame, no cheaper for the
                # attacker than sending a fresh message.
                self.equivocation_dropped += 1
                self._evidence(msg.source, "qbft_equivocation")
            return False
        # A PRE-PREPARE must come from the round's leader; storing
        # non-leader proposals would let any peer squat PRE-PREPARE slots
        # (and a validly-signed one is a protocol violation by its sender).
        if msg.type == MsgType.PRE_PREPARE and msg.source != self.d.leader(
            self.instance, msg.round
        ):
            if self._sender_authentic(msg):
                self._evidence(msg.source, "qbft_malformed")
            return False
        # Bound + dedup justifications BEFORE signature verification: a
        # protocol-honest PRE-PREPARE carries at most a ROUND-CHANGE quorum
        # plus a PREPARE quorum (<= 2n distinct (type, source, round)
        # slots); anything larger or duplicated is a CPU-amplification
        # attack (each entry costs an ECDSA verify). Attribution costs one
        # outer verify — same price the sender paid to send the frame.
        if len(msg.justification) > 2 * self.d.nodes:
            if self._sender_authentic(msg):
                self._evidence(msg.source, "qbft_malformed")
            return False
        seen: set = set()
        for j in msg.justification:
            jkey = (j.type, j.source, j.round)
            if not (0 <= j.source < self.d.nodes) or jkey in seen:
                if self._sender_authentic(msg):
                    self._evidence(msg.source, "qbft_malformed")
                return False
            seen.add(jkey)
        # Per-sender stored bound, checked before the full is_valid so a
        # flood costs no justification-recursion verifies. Evidence is
        # attributed once per source (one outer verify, then free drops).
        n_stored = self._stored_per_source.get(msg.source, 0)
        if n_stored >= self.d.max_stored_per_source:
            self.flood_dropped += 1
            if msg.source not in self._flood_flagged and (
                self._sender_authentic(msg)
            ):
                self._flood_flagged.add(msg.source)
                self._evidence(msg.source, "qbft_flood")
            return False
        if not self.d.is_valid(msg):
            if self.d.verify_sender is not None and self.d.verify_sender(
                msg
            ):
                # The outer signature verifies but a piggybacked
                # justification does not: the sender forged its
                # justification (a garbage frame would have failed the
                # outer check too, proving nothing about the source).
                self._evidence(msg.source, "qbft_forged_justification")
            return False
        self._stored_per_source[msg.source] = n_stored + 1
        self.msgs[key] = msg
        return True

    # -- justification rules (paper §4.4) ---------------------------------

    def _highest_prepared(self, rcs: Sequence[Msg]) -> Msg | None:
        best = None
        for m in rcs:
            if m.prepared_round > 0 and (
                best is None or m.prepared_round > best.prepared_round
            ):
                best = m
        return best

    def _justify_preprepare(self, msg: Msg) -> bool:
        if msg.round == 1:
            return True
        rcs = [
            j
            for j in msg.justification
            if j.type == MsgType.ROUND_CHANGE
            and j.round == msg.round
            and j.instance == self.instance
        ]
        # distinct senders, quorum
        senders = {j.source for j in rcs}
        if len(senders) < self.d.quorum:
            return False
        best = self._highest_prepared(rcs)
        if best is None:
            return True  # free to propose anything
        if msg.value != best.prepared_value:
            return False
        # the claimed prepared value must be backed by a PREPARE quorum
        # FROM THIS INSTANCE — without the instance check a byzantine
        # leader could replay a validly-signed PREPARE quorum recorded in
        # a different instance to justify a foreign value here
        prepares = [
            j
            for j in msg.justification
            if j.type == MsgType.PREPARE
            and j.instance == self.instance
            and j.round == best.prepared_round
            and j.value == best.prepared_value
        ]
        return len({j.source for j in prepares}) >= self.d.quorum

    # -- main loop --------------------------------------------------------

    async def run(self, value, value_ch) -> Hashable:
        loop = asyncio.get_running_loop()
        self.decided = loop.create_future()
        self.input_value = value
        timer_task: asyncio.Task | None = None
        rt = (
            self.d.new_timer()
            if self.d.new_timer is not None
            else _FnTimer(self.d.timeout)
        )

        async def round_timer(rnd: int, duration: float):
            await asyncio.sleep(duration)
            await self._on_timeout(rnd)

        def restart_timer():
            nonlocal timer_task
            if timer_task is not None:
                timer_task.cancel()
            self._timer_round = self.round
            # duration computed NOW, not when the task first runs: the
            # eager-dlinear timer must anchor a round's first deadline to
            # the moment the round starts (its whole point is aligning
            # deadlines with round starts, not with scheduler latency)
            d = rt.duration(self.round, loop.time())
            timer_task = asyncio.create_task(round_timer(self.round, d))

        self._restart_timer = restart_timer
        restart_timer()

        if value is None and value_ch is not None:

            async def await_value():
                v = await value_ch
                self.input_value = v
                await self._maybe_propose()

            value_task = asyncio.create_task(await_value())
        else:
            value_task = None

        await self._maybe_propose()

        try:
            while not self.decided.done():
                get = asyncio.create_task(self.t.inbox.get())
                done, _ = await asyncio.wait(
                    {get, self.decided},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if self.decided.done():
                    get.cancel()
                    break
                msg = get.result()
                self.t._consumed(msg)
                if self._accept(msg):
                    await self._on_msg(msg)
                # Re-arm only if _on_msg didn't already arm this round
                # (the justified-pre-prepare rule restarts inline, ref
                # qbft.go:318-319 — re-arming again here would double the
                # eager-dlinear deadline twice for one rule firing).
                if self.round != self._timer_round:
                    restart_timer()
                    # Messages for the new round may already be buffered in
                    # self.msgs (they arrived while we were behind); re-run
                    # the upon-rules against the stored state.
                    await self._reevaluate()
            return self.decided.result()
        finally:
            if timer_task is not None:
                timer_task.cancel()
            if value_task is not None:
                value_task.cancel()

    async def _reevaluate(self) -> None:
        """Re-run upon-rules for the current round against stored messages
        (after a round catch-up, quorums may already be present)."""
        for m in self._collect(MsgType.PRE_PREPARE, self.round):
            await self._on_msg(m)
        for m in self._collect(MsgType.PREPARE, self.round)[:1]:
            await self._on_msg(m)
        for rnd in {r for (t, _, r) in self.msgs if t == MsgType.COMMIT}:
            for m in self._collect(MsgType.COMMIT, rnd)[:1]:
                await self._on_msg(m)
        await self._maybe_propose()

    async def _maybe_propose(self) -> None:
        """Leader of round 1 sends the PRE-PREPARE when it has a value."""
        if (
            self.input_value is not None
            and self.d.leader(self.instance, self.round) == self.node
            and self.round not in self.sent_preprepare
        ):
            just = ()
            if self.round > 1:
                just = self._round_change_justification(self.round)
                if just is None:
                    return
            self.sent_preprepare.add(self.round)
            await self._send(
                Msg(
                    MsgType.PRE_PREPARE,
                    self.instance,
                    self.node,
                    self.round,
                    self._leader_value(self.round),
                    justification=tuple(just),
                )
            )

    def _leader_value(self, rnd: int):
        rcs = self._collect(MsgType.ROUND_CHANGE, rnd)
        best = self._highest_prepared(rcs)
        if best is not None:
            return best.prepared_value
        return self.input_value

    def _round_change_justification(self, rnd: int):
        rcs = self._collect(MsgType.ROUND_CHANGE, rnd)
        if len({m.source for m in rcs}) < self.d.quorum:
            return None
        just = list(rcs)
        best = self._highest_prepared(rcs)
        if best is not None:
            just.extend(best.justification)  # piggybacked PREPARE quorum
        return just

    async def _on_msg(self, msg: Msg) -> None:
        d = self.d
        # uponRule: PRE-PREPARE from the round's leader, justified.
        if msg.type == MsgType.PRE_PREPARE:
            if msg.source != d.leader(self.instance, msg.round):
                return
            if not self._justify_preprepare(msg):
                return
            if msg.round < self.round:
                return
            if msg.round > self.round:
                # catch up to the pre-prepared round (paper: accept
                # justified pre-prepare for a future round)
                self.round = msg.round
            if self.round not in self.sent_prepare:
                self.sent_prepare.add(self.round)
                # Justified pre-prepare restarts the round timer (ref:
                # qbft.go:318-319). Once per round (the sent_prepare
                # guard is the ref's isDuplicatedRule): with the
                # increasing timer this is a full reset; with the
                # eager-double-linear timer it extends the round to
                # double its first deadline instead.
                if self._restart_timer is not None:
                    self._restart_timer()
                await self._send(
                    Msg(
                        MsgType.PREPARE,
                        self.instance,
                        self.node,
                        self.round,
                        msg.value,
                    )
                )

        elif msg.type == MsgType.PREPARE:
            v = self._quorum_value(MsgType.PREPARE, self.round)
            if v is not None and self.round not in self.sent_commit:
                self.prepared_round = self.round
                self.prepared_value = v
                self.prepare_quorum_just = tuple(
                    m
                    for m in self._collect(MsgType.PREPARE, self.round)
                    if m.value == v
                )
                self.sent_commit.add(self.round)
                await self._send(
                    Msg(
                        MsgType.COMMIT,
                        self.instance,
                        self.node,
                        self.round,
                        v,
                    )
                )

        elif msg.type == MsgType.COMMIT:
            # decide on any round's commit quorum
            v = self._quorum_value(MsgType.COMMIT, msg.round)
            if v is not None and not self.decided.done():
                self.decided.set_result(v)

        elif msg.type == MsgType.ROUND_CHANGE:
            await self._on_round_change(msg)

    async def _on_round_change(self, msg: Msg) -> None:
        d = self.d
        # f+1 round-changes ahead of us: jump to the smallest of them.
        ahead = [
            m
            for m in (
                m
                for (t, _, r), m in self.msgs.items()
                if t == MsgType.ROUND_CHANGE and r > self.round
            )
        ]
        if len({m.source for m in ahead}) >= d.faulty + 1:
            self.round = min(m.round for m in ahead)
            await self._broadcast_round_change()

        # leader of msg.round with a quorum: send justified PRE-PREPARE.
        if (
            msg.round >= self.round
            and d.leader(self.instance, msg.round) == self.node
            and msg.round not in self.sent_preprepare
        ):
            just = self._round_change_justification(msg.round)
            if just is not None and (
                self._leader_value(msg.round) is not None
            ):
                self.round = msg.round
                self.sent_preprepare.add(msg.round)
                await self._send(
                    Msg(
                        MsgType.PRE_PREPARE,
                        self.instance,
                        self.node,
                        msg.round,
                        self._leader_value(msg.round),
                        justification=tuple(just),
                    )
                )

    async def _on_timeout(self, rnd: int) -> None:
        if self.decided.done() or rnd != self.round:
            return
        self.round += 1
        self._restart_timer()
        await self._broadcast_round_change()

    async def _broadcast_round_change(self) -> None:
        if self.round in self.sent_round_change:
            return
        self.sent_round_change.add(self.round)
        await self._send(
            Msg(
                MsgType.ROUND_CHANGE,
                self.instance,
                self.node,
                self.round,
                prepared_round=self.prepared_round,
                prepared_value=self.prepared_value,
                justification=self.prepare_quorum_just,
            )
        )
