"""QBFT: a pure, transport-agnostic implementation of the Istanbul BFT
consensus algorithm (Moniz, arXiv:2002.03613).

Plays the role of ref: core/qbft/qbft.go — a generic engine with zero
dependencies, driven entirely through a Definition (validation, leader
selection, timers) and a Transport (broadcast + inbound queue), so the
simnet runs it over in-memory channels and production over the p2p layer.
This is a from-scratch implementation of the published algorithm, asyncio
style: one `run` coroutine per consensus instance.

Quorum: ceil(2n/3); tolerates floor((n-1)/3) byzantine nodes.

The subtle parts, implemented per the paper:
  * PRE-PREPARE justification for round > 1 (a quorum of ROUND-CHANGEs,
    and the proposed value must match the highest prepared value among
    them, which itself must be justified by a PREPARE quorum);
  * ROUND-CHANGE carries (prepared_round, prepared_value) plus the
    PREPARE messages justifying them;
  * f+1 ROUND-CHANGEs ahead of us pull us into the smallest such round.
"""

from __future__ import annotations

import asyncio
import enum
import math
from dataclasses import dataclass, field, replace
from typing import Awaitable, Callable, Hashable, Sequence


class MsgType(enum.IntEnum):
    PRE_PREPARE = 1
    PREPARE = 2
    COMMIT = 3
    ROUND_CHANGE = 4


@dataclass(frozen=True)
class Msg:
    """One QBFT message. `value` is the proposed value (hashable; the
    adapter layer uses 32-byte hashes with values carried out-of-band, ref:
    core/consensus/qbft/transport.go values-by-hash). Justification carries
    piggybacked messages for PRE-PREPARE/ROUND-CHANGE rules."""

    type: MsgType
    instance: Hashable
    source: int  # node index 0..n-1
    round: int
    value: Hashable | None = None
    prepared_round: int = 0
    prepared_value: Hashable | None = None
    justification: tuple["Msg", ...] = ()


@dataclass
class Definition:
    """Parameters binding the pure engine to an environment."""

    nodes: int
    leader: Callable[[Hashable, int], int]  # (instance, round) -> node idx
    # round -> timeout seconds (ref-equivalent default: 0.75 + 0.25*round)
    timeout: Callable[[int], float] = lambda r: 0.75 + 0.25 * r
    is_valid: Callable[[Msg], bool] = lambda m: True

    @property
    def quorum(self) -> int:
        return math.ceil(2 * self.nodes / 3)

    @property
    def faulty(self) -> int:
        return (self.nodes - 1) // 3


class Transport:
    """Broadcast + inbound queue. The engine owns no sockets."""

    def __init__(self, broadcast: Callable[[Msg], Awaitable[None]]):
        self.broadcast = broadcast
        self.inbox: asyncio.Queue[Msg] = asyncio.Queue()


async def run(
    defn: Definition,
    transport: Transport,
    instance: Hashable,
    node: int,
    value: Hashable | None,
    value_ch: asyncio.Future | None = None,
) -> Hashable:
    """Run one QBFT instance until it decides; returns the decided value.

    `value` is this node's proposal input (may be None initially with a
    `value_ch` future supplying it later — the participate-then-propose
    pattern, ref: core/consensus/qbft/qbft.go Propose vs Participate).
    """
    engine = _Engine(defn, transport, instance, node)
    return await engine.run(value, value_ch)


class _Engine:
    def __init__(self, defn: Definition, transport: Transport, instance, node: int):
        self.d = defn
        self.t = transport
        self.instance = instance
        self.node = node
        self.round = 1
        self.prepared_round = 0
        self.prepared_value = None
        self.prepare_quorum_just: tuple[Msg, ...] = ()
        self.input_value = None
        # dedup: (type, source, round) -> Msg (first wins per slot)
        self.msgs: dict[tuple[MsgType, int, int], Msg] = {}
        self.sent_prepare: set[int] = set()
        self.sent_commit: set[int] = set()
        self.sent_preprepare: set[int] = set()
        self.sent_round_change: set[int] = set()
        self.decided: asyncio.Future = None  # type: ignore

    # -- helpers ----------------------------------------------------------

    def _collect(self, typ: MsgType, rnd: int) -> list[Msg]:
        return [
            m
            for (t, _, r), m in self.msgs.items()
            if t == typ and r == rnd
        ]

    def _quorum_value(self, typ: MsgType, rnd: int) -> Hashable | None:
        """Value (or hash) agreed by a quorum of messages of typ@rnd."""
        counts: dict = {}
        for m in self._collect(typ, rnd):
            counts[m.value] = counts.get(m.value, 0) + 1
            if counts[m.value] >= self.d.quorum:
                return m.value
        return None

    async def _send(self, msg: Msg) -> None:
        await self.t.broadcast(msg)
        # Loopback: our own message must also drive the upon-rules (it may
        # be the final piece of a quorum). Recursion is bounded by the
        # sent_* dedup sets.
        if self._accept(msg):
            await self._on_msg(msg)

    def _accept(self, msg: Msg) -> bool:
        if msg.instance != self.instance:
            return False
        if not (0 <= msg.source < self.d.nodes):
            return False
        if not self.d.is_valid(msg):
            return False
        key = (msg.type, msg.source, msg.round)
        if key in self.msgs:
            return False
        self.msgs[key] = msg
        return True

    # -- justification rules (paper §4.4) ---------------------------------

    def _highest_prepared(self, rcs: Sequence[Msg]) -> Msg | None:
        best = None
        for m in rcs:
            if m.prepared_round > 0 and (
                best is None or m.prepared_round > best.prepared_round
            ):
                best = m
        return best

    def _justify_preprepare(self, msg: Msg) -> bool:
        if msg.round == 1:
            return True
        rcs = [
            j
            for j in msg.justification
            if j.type == MsgType.ROUND_CHANGE
            and j.round == msg.round
            and j.instance == self.instance
        ]
        # distinct senders, quorum
        senders = {j.source for j in rcs}
        if len(senders) < self.d.quorum:
            return False
        best = self._highest_prepared(rcs)
        if best is None:
            return True  # free to propose anything
        if msg.value != best.prepared_value:
            return False
        # the claimed prepared value must be backed by a PREPARE quorum
        prepares = [
            j
            for j in msg.justification
            if j.type == MsgType.PREPARE
            and j.round == best.prepared_round
            and j.value == best.prepared_value
        ]
        return len({j.source for j in prepares}) >= self.d.quorum

    # -- main loop --------------------------------------------------------

    async def run(self, value, value_ch) -> Hashable:
        loop = asyncio.get_running_loop()
        self.decided = loop.create_future()
        self.input_value = value
        timer_task: asyncio.Task | None = None

        async def round_timer(rnd: int):
            await asyncio.sleep(self.d.timeout(rnd))
            await self._on_timeout(rnd)

        def restart_timer():
            nonlocal timer_task
            if timer_task is not None:
                timer_task.cancel()
            timer_task = asyncio.create_task(round_timer(self.round))

        self._restart_timer = restart_timer
        restart_timer()

        if value is None and value_ch is not None:

            async def await_value():
                v = await value_ch
                self.input_value = v
                await self._maybe_propose()

            value_task = asyncio.create_task(await_value())
        else:
            value_task = None

        await self._maybe_propose()

        try:
            while not self.decided.done():
                get = asyncio.create_task(self.t.inbox.get())
                done, _ = await asyncio.wait(
                    {get, self.decided},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if self.decided.done():
                    get.cancel()
                    break
                msg = get.result()
                prev_round = self.round
                if self._accept(msg):
                    await self._on_msg(msg)
                if self.round != prev_round:
                    restart_timer()
            return self.decided.result()
        finally:
            if timer_task is not None:
                timer_task.cancel()
            if value_task is not None:
                value_task.cancel()

    async def _maybe_propose(self) -> None:
        """Leader of round 1 sends the PRE-PREPARE when it has a value."""
        if (
            self.input_value is not None
            and self.d.leader(self.instance, self.round) == self.node
            and self.round not in self.sent_preprepare
        ):
            just = ()
            if self.round > 1:
                just = self._round_change_justification(self.round)
                if just is None:
                    return
            self.sent_preprepare.add(self.round)
            await self._send(
                Msg(
                    MsgType.PRE_PREPARE,
                    self.instance,
                    self.node,
                    self.round,
                    self._leader_value(self.round),
                    justification=tuple(just),
                )
            )

    def _leader_value(self, rnd: int):
        rcs = self._collect(MsgType.ROUND_CHANGE, rnd)
        best = self._highest_prepared(rcs)
        if best is not None:
            return best.prepared_value
        return self.input_value

    def _round_change_justification(self, rnd: int):
        rcs = self._collect(MsgType.ROUND_CHANGE, rnd)
        if len({m.source for m in rcs}) < self.d.quorum:
            return None
        just = list(rcs)
        best = self._highest_prepared(rcs)
        if best is not None:
            just.extend(best.justification)  # piggybacked PREPARE quorum
        return just

    async def _on_msg(self, msg: Msg) -> None:
        d = self.d
        # uponRule: PRE-PREPARE from the round's leader, justified.
        if msg.type == MsgType.PRE_PREPARE:
            if msg.source != d.leader(self.instance, msg.round):
                return
            if not self._justify_preprepare(msg):
                return
            if msg.round < self.round:
                return
            if msg.round > self.round:
                # catch up to the pre-prepared round (paper: accept
                # justified pre-prepare for a future round)
                self.round = msg.round
            if self.round not in self.sent_prepare:
                self.sent_prepare.add(self.round)
                await self._send(
                    Msg(
                        MsgType.PREPARE,
                        self.instance,
                        self.node,
                        self.round,
                        msg.value,
                    )
                )

        elif msg.type == MsgType.PREPARE:
            v = self._quorum_value(MsgType.PREPARE, self.round)
            if v is not None and self.round not in self.sent_commit:
                self.prepared_round = self.round
                self.prepared_value = v
                self.prepare_quorum_just = tuple(
                    m
                    for m in self._collect(MsgType.PREPARE, self.round)
                    if m.value == v
                )
                self.sent_commit.add(self.round)
                await self._send(
                    Msg(
                        MsgType.COMMIT,
                        self.instance,
                        self.node,
                        self.round,
                        v,
                    )
                )

        elif msg.type == MsgType.COMMIT:
            # decide on any round's commit quorum
            v = self._quorum_value(MsgType.COMMIT, msg.round)
            if v is not None and not self.decided.done():
                self.decided.set_result(v)

        elif msg.type == MsgType.ROUND_CHANGE:
            await self._on_round_change(msg)

    async def _on_round_change(self, msg: Msg) -> None:
        d = self.d
        # f+1 round-changes ahead of us: jump to the smallest of them.
        ahead = [
            m
            for m in (
                m
                for (t, _, r), m in self.msgs.items()
                if t == MsgType.ROUND_CHANGE and r > self.round
            )
        ]
        if len({m.source for m in ahead}) >= d.faulty + 1:
            self.round = min(m.round for m in ahead)
            await self._broadcast_round_change()

        # leader of msg.round with a quorum: send justified PRE-PREPARE.
        if (
            msg.round >= self.round
            and d.leader(self.instance, msg.round) == self.node
            and msg.round not in self.sent_preprepare
        ):
            just = self._round_change_justification(msg.round)
            if just is not None and (
                self._leader_value(msg.round) is not None
            ):
                self.round = msg.round
                self.sent_preprepare.add(msg.round)
                await self._send(
                    Msg(
                        MsgType.PRE_PREPARE,
                        self.instance,
                        self.node,
                        msg.round,
                        self._leader_value(msg.round),
                        justification=tuple(just),
                    )
                )

    async def _on_timeout(self, rnd: int) -> None:
        if self.decided.done() or rnd != self.round:
            return
        self.round += 1
        self._restart_timer()
        await self._broadcast_round_change()

    async def _broadcast_round_change(self) -> None:
        if self.round in self.sent_round_change:
            return
        self.sent_round_change.add(self.round)
        await self._send(
            Msg(
                MsgType.ROUND_CHANGE,
                self.instance,
                self.node,
                self.round,
                prepared_round=self.prepared_round,
                prepared_value=self.prepared_value,
                justification=self.prepare_quorum_just,
            )
        )
