"""Priority protocol: cluster-wide preference negotiation.

Mirrors ref: core/priority — each node exchanges signed priority messages
listing its ordered preferences per topic (prioritiser.go:326), computes
the cluster-wide ordering (calculate.go: priorities supported by at least
quorum peers, ordered by aggregate position score), then agrees on the
result via a consensus instance. Infosync (ref: core/infosync) triggers it
in the last slot of each epoch and feeds the result to the consensus
controller for protocol switching (ref: app/app.go:650-668).
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Sequence

from charon_tpu.core.types import Duty, DutyType


@dataclass(frozen=True)
class PriorityMsg:
    peer_idx: int
    slot: int
    topics: tuple[tuple[str, tuple[str, ...]], ...]  # (topic, ordered prefs)


@dataclass(frozen=True)
class TopicResult:
    topic: str
    priorities: tuple[str, ...]  # cluster-agreed order


def calculate(msgs: Sequence[PriorityMsg], quorum: int) -> list[TopicResult]:
    """Cluster-wide ordering (ref: core/priority/calculate.go:205):
    a priority is included iff at least `quorum` peers list it; included
    priorities are ordered by total score (higher list positions score
    more), ties broken lexically for determinism."""
    by_topic: dict[str, list[tuple[int, tuple[str, ...]]]] = defaultdict(list)
    for m in msgs:
        for topic, prefs in m.topics:
            by_topic[topic].append((m.peer_idx, prefs))

    out = []
    for topic in sorted(by_topic):
        counts: dict[str, int] = defaultdict(int)
        scores: dict[str, int] = defaultdict(int)
        for _, prefs in by_topic[topic]:
            for pos, p in enumerate(prefs):
                counts[p] += 1
                scores[p] += len(prefs) - pos
        included = [p for p, c in counts.items() if c >= quorum]
        included.sort(key=lambda p: (-scores[p], p))
        out.append(TopicResult(topic=topic, priorities=tuple(included)))
    return out


class Prioritiser:
    """exchange: async callable broadcasting our msg and returning all
    peers' msgs (the p2p or in-memory fabric); consensus: object with
    propose(duty, value_set) + subscribe(cb) — the cluster's consensus
    component, reused for agreement on the result."""

    def __init__(
        self,
        node_idx: int,
        quorum: int,
        exchange,
        consensus,
        topics_fn: Callable[[], dict[str, list[str]]],
        timeout: float = 6.0,  # ref: app/app.go:610 priority exchange timeout
    ) -> None:
        self.node_idx = node_idx
        self.quorum = quorum
        self.exchange = exchange
        self.consensus = consensus
        self.topics_fn = topics_fn
        self.timeout = timeout
        self._subs: list = []
        consensus.subscribe(self._on_decided)

    def subscribe(self, sub) -> None:
        """sub(slot, list[TopicResult])"""
        self._subs.append(sub)

    async def prioritise(self, slot: int) -> None:
        """One negotiation round (ref: prioritiser.go:326 Prioritise)."""
        topics = tuple(
            (t, tuple(prefs)) for t, prefs in sorted(self.topics_fn().items())
        )
        my_msg = PriorityMsg(self.node_idx, slot, topics)
        msgs = await asyncio.wait_for(
            self.exchange(slot, my_msg), self.timeout
        )
        result = calculate(list(msgs.values()), self.quorum)
        duty = Duty(slot, DutyType.INFO_SYNC)
        await self.consensus.propose(
            duty, {"priority": tuple(result)}
        )

    async def _on_decided(self, duty: Duty, value_set) -> None:
        if duty.type != DutyType.INFO_SYNC:
            return
        result = value_set.get("priority")
        if result is None:
            return
        for sub in self._subs:
            await sub(duty.slot, list(result))


class InfoSync:
    """Triggers prioritisation in the last slot of each epoch
    (ref: core/infosync/infosync.go:145; wiring app/app.go:638-644)."""

    TOPIC_PROTOCOL = "consensus_protocol"
    TOPIC_VERSION = "node_version"

    def __init__(self, prioritiser: Prioritiser) -> None:
        self.prioritiser = prioritiser
        self._last_epoch = -1

    async def on_slot(self, slot) -> None:
        if not slot.is_last_in_epoch():
            return
        if slot.epoch == self._last_epoch:
            return
        self._last_epoch = slot.epoch
        try:
            await self.prioritiser.prioritise(slot.slot)
        except asyncio.TimeoutError:
            pass  # negotiation is best-effort per epoch


def protocol_switcher(controller):
    """Priority subscriber that switches the consensus protocol to the
    cluster's top choice (ref: app/app.go:650-668)."""

    async def on_result(slot: int, results: list[TopicResult]) -> None:
        for r in results:
            if r.topic == InfoSync.TOPIC_PROTOCOL and r.priorities:
                for proto in r.priorities:
                    if controller.set_current_for_protocol(proto):
                        break

    return on_result
