"""Priority protocol: cluster-wide preference negotiation.

Mirrors ref: core/priority — each node exchanges signed priority messages
listing its ordered preferences per topic (prioritiser.go:326), computes
the cluster-wide ordering (calculate.go: priorities supported by at least
quorum peers, ordered by aggregate position score), then agrees on the
result via a consensus instance. Infosync (ref: core/infosync) triggers it
in the last slot of each epoch and feeds the result to the consensus
controller for protocol switching (ref: app/app.go:650-668).
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Sequence

from charon_tpu.core.types import Duty, DutyType


@dataclass(frozen=True)
class PriorityMsg:
    peer_idx: int
    slot: int
    topics: tuple[tuple[str, tuple[str, ...]], ...]  # (topic, ordered prefs)


@dataclass(frozen=True)
class TopicResult:
    topic: str
    priorities: tuple[str, ...]  # cluster-agreed order
    scores: tuple[int, ...] = ()  # per-priority aggregate score
                                  # (ref: PriorityScoredResult.Score)


# Weight a supporting peer far above any relative-position contribution,
# so the aggregate score orders by COUNT first and by overall list
# position only within equal counts (ref: calculate.go:17-19
# maxPriorities/countWeight — one number encodes count-then-position).
MAX_PRIORITIES = 1000
COUNT_WEIGHT = MAX_PRIORITIES


class PriorityError(Exception):
    """Invalid priority message set (ref: calculate.go validateMsgs)."""


def validate_msgs(msgs: Sequence[PriorityMsg]) -> None:
    """Reference validation rules (ref: calculate.go:141-192): non-empty
    input, identical slots, no duplicate peers, per-peer unique topics,
    per-topic unique priorities, at most MAX_PRIORITIES priorities."""
    if not msgs:
        raise PriorityError("messages empty")
    slot = msgs[0].slot
    peers: set[int] = set()
    for m in msgs:
        if m.slot != slot:
            raise PriorityError("mismatching slots")
        if m.peer_idx in peers:
            raise PriorityError("duplicate peer")
        peers.add(m.peer_idx)
        topics_seen: set[str] = set()
        for topic, prefs in m.topics:
            if topic in topics_seen:
                raise PriorityError("duplicate topic")
            topics_seen.add(topic)
            if len(prefs) >= MAX_PRIORITIES:
                raise PriorityError("max priorities reached")
            if len(set(prefs)) != len(prefs):
                raise PriorityError("duplicate priority")


def calculate(msgs: Sequence[PriorityMsg], quorum: int) -> list[TopicResult]:
    """Deterministic cluster-wide ordering (ref: calculate.go:25-99
    calculateResult): a priority is included iff at least `quorum` peers
    list it, and included priorities order by supporter COUNT first,
    positional preferredness second, lexical tie-break last.

    Two deliberate strictness improvements over the reference's single
    blended score (countWeight - order summed per listing): the ref
    formula is only "effectively count-then-position" for short lists —
    deep positions can push a quorum-supported priority below its
    inclusion threshold and position sums can cross count boundaries —
    so count and position score are tracked separately here, and ties
    break lexically where the reference's unstable sort left equal
    scores unordered. Topics are emitted in sorted order
    (ref: orderTopicResults)."""
    validate_msgs(msgs)

    by_topic: dict[str, list[tuple[str, ...]]] = defaultdict(list)
    for m in sorted(msgs, key=lambda m: m.peer_idx):  # ref: sortInput
        for topic, prefs in m.topics:
            by_topic[topic].append(prefs)

    out = []
    for topic in sorted(by_topic):
        counts: dict[str, int] = defaultdict(int)
        pos_score: dict[str, int] = defaultdict(int)
        for prefs in by_topic[topic]:
            for pos, p in enumerate(prefs):
                counts[p] += 1
                pos_score[p] += MAX_PRIORITIES - 1 - pos
        included = [p for p, c in counts.items() if c >= quorum]
        included.sort(key=lambda p: (-counts[p], -pos_score[p], p))
        out.append(
            TopicResult(
                topic=topic,
                priorities=tuple(included),
                # blended score for observability, count-dominant
                # (ref: PriorityScoredResult.Score)
                scores=tuple(
                    counts[p] * COUNT_WEIGHT + pos_score[p]
                    for p in included
                ),
            )
        )
    return out


class Prioritiser:
    """exchange: async callable broadcasting our msg and returning all
    peers' msgs (the p2p or in-memory fabric); consensus: object with
    propose(duty, value_set) + subscribe(cb) — the cluster's consensus
    component, reused for agreement on the result."""

    def __init__(
        self,
        node_idx: int,
        quorum: int,
        exchange,
        consensus,
        topics_fn: Callable[[], dict[str, list[str]]],
        timeout: float = 6.0,  # ref: app/app.go:610 priority exchange timeout
        on_duty_done: Callable[[Duty], None] | None = None,
    ) -> None:
        self.node_idx = node_idx
        self.quorum = quorum
        self.exchange = exchange
        self.consensus = consensus
        self.topics_fn = topics_fn
        self.timeout = timeout
        # cleanup hook: the INFO_SYNC duty is Prioritiser-created (the
        # scheduler never emits it), so nothing else registers it with
        # the deadliner — without this hook the consensus instance and
        # tracker events for it would accumulate one per epoch forever
        self.on_duty_done = on_duty_done
        self._subs: list = []
        consensus.subscribe(self._on_decided)

    def subscribe(self, sub) -> None:
        """sub(slot, list[TopicResult])"""
        self._subs.append(sub)

    async def prioritise(self, slot: int) -> None:
        """One negotiation round (ref: prioritiser.go:326 Prioritise).
        Peers that do not answer within the timeout are simply absent
        from the input set — quorum support decides inclusion."""
        topics = tuple(
            (t, tuple(prefs)) for t, prefs in sorted(self.topics_fn().items())
        )
        my_msg = PriorityMsg(self.node_idx, slot, topics)
        msgs = await asyncio.wait_for(
            self.exchange(slot, my_msg), self.timeout
        )
        # drop malformed peer contributions instead of failing the
        # round: validate each peer's msg alone, then the joint set
        good = []
        for m in msgs.values():
            try:
                validate_msgs([m])
            except PriorityError:
                continue
            if m.slot == slot:
                good.append(m)
        result = calculate(good, self.quorum)
        duty = Duty(slot, DutyType.INFO_SYNC)
        try:
            await self.consensus.propose(
                duty, {"priority": tuple(result)}
            )
        finally:
            if self.on_duty_done is not None:
                self.on_duty_done(duty)

    async def _on_decided(self, duty: Duty, value_set) -> None:
        if duty.type != DutyType.INFO_SYNC:
            return
        result = value_set.get("priority")
        if result is None:
            return
        for sub in self._subs:
            await sub(duty.slot, list(result))


class InfoSync:
    """Triggers prioritisation in the last slot of each epoch
    (ref: core/infosync/infosync.go:145; wiring app/app.go:638-644)."""

    TOPIC_PROTOCOL = "consensus_protocol"
    TOPIC_VERSION = "node_version"

    def __init__(self, prioritiser: Prioritiser) -> None:
        self.prioritiser = prioritiser
        self._last_epoch = -1
        self._task: asyncio.Task | None = None

    async def on_slot(self, slot) -> None:
        if not slot.is_last_in_epoch():
            return
        if slot.epoch == self._last_epoch:
            return
        self._last_epoch = slot.epoch
        # background: negotiation (up to the exchange timeout) must not
        # delay the scheduler's duty spawning for this slot, and NO
        # failure may escape into the scheduler loop — negotiation is
        # best-effort per epoch
        self._task = asyncio.create_task(self._run(slot.slot))

    async def _run(self, slot: int) -> None:
        try:
            await self.prioritiser.prioritise(slot)
        except asyncio.TimeoutError:
            pass
        except Exception as e:  # noqa: BLE001 — never kill the caller
            from charon_tpu.app import log

            log.warn(
                "priority negotiation failed",
                topic="infosync",
                slot=slot,
                err=f"{type(e).__name__}: {str(e)[:160]}",
            )


PRIORITY_PROTOCOL = "priority/1.0.0"


class MemPriorityFabric:
    """In-process exchange for the simnet: every joined node contributes
    its message for a slot and exchange() resolves once all have (or the
    Prioritiser's timeout fires with whatever arrived)."""

    def __init__(self) -> None:
        self.n = 0
        self._msgs: dict[int, dict[int, PriorityMsg]] = defaultdict(dict)
        self._events: dict[int, asyncio.Event] = {}

    def join(self) -> None:
        self.n += 1

    async def exchange(self, slot: int, my_msg: PriorityMsg):
        got = self._msgs[slot]
        got[my_msg.peer_idx] = my_msg
        ev = self._events.setdefault(slot, asyncio.Event())
        if len(got) >= self.n:
            ev.set()
        await ev.wait()
        return dict(got)


class P2PPriorityExchange:
    """Priority-message gather over the p2p mesh (production fabric;
    ref: core/priority/prioritiser.go exchange over libp2p streams).

    Our message for the slot is stored, then peers are polled with a
    typed request; each peer's handler answers with its own message for
    that slot once it has computed one. The Prioritiser bounds the whole
    gather with its timeout, so polling simply retries until then."""

    def __init__(
        self,
        node,
        poll_interval: float = 0.5,
        gather_timeout: float = 4.0,
    ) -> None:
        self.node = node
        self.poll_interval = poll_interval
        # returns the PARTIAL set once this budget elapses: an offline
        # peer must not starve negotiation — calculate() is quorum-based
        # and works from whatever arrived (kept below the Prioritiser's
        # 6 s timeout so wait_for never discards a gathered set)
        self.gather_timeout = gather_timeout
        self._mine: dict[int, PriorityMsg] = {}
        node.register_handler(PRIORITY_PROTOCOL, self._handle)

    async def _handle(self, from_idx: int, msg):
        slot = msg.get("slot") if isinstance(msg, dict) else None
        mine = self._mine.get(slot)
        return {"msg": mine} if mine is not None else {"msg": None}

    async def exchange(self, slot: int, my_msg: PriorityMsg):
        self._mine[slot] = my_msg
        # bounded memory: keep only the most recent few rounds
        for old in sorted(self._mine)[:-4]:
            self._mine.pop(old, None)
        got = {my_msg.peer_idx: my_msg}
        pending = set(self.node.peers)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.gather_timeout
        while pending and loop.time() < deadline:
            for idx in sorted(pending):
                try:
                    resp = await self.node.send(
                        idx,
                        PRIORITY_PROTOCOL,
                        {"slot": slot},
                        await_response=True,
                    )
                except Exception:
                    continue
                peer_msg = resp.get("msg") if isinstance(resp, dict) else None
                if isinstance(peer_msg, PriorityMsg) and peer_msg.slot == slot:
                    got[peer_msg.peer_idx] = peer_msg
                    pending.discard(idx)
            if pending:
                await asyncio.sleep(
                    min(self.poll_interval, max(0.0, deadline - loop.time()))
                )
        return got


def order_protocol_prefs(registered: list[str], preferred: str) -> list[str]:
    """Supported protocols most-preferred first: a cluster-level
    preference (the v1.1 definition's hash-covered consensus_protocol)
    outranks the node default; an unsupported or empty preference leaves
    the order untouched (ref: the cluster consensus preference feeds the
    node's priority proposal ahead of its defaults)."""
    prefs = list(registered)
    if preferred in prefs:
        prefs.remove(preferred)
        prefs.insert(0, preferred)
    return prefs


def protocol_switcher(controller):
    """Priority subscriber that switches the consensus protocol to the
    cluster's top choice (ref: app/app.go:650-668)."""

    async def on_result(slot: int, results: list[TopicResult]) -> None:
        for r in results:
            if r.topic == InfoSync.TOPIC_PROTOCOL and r.priorities:
                for proto in r.priorities:
                    if controller.set_current_for_protocol(proto):
                        break

    return on_result
