"""ParSigEx: partial-signature exchange between cluster peers.

Mirrors ref: core/parsigex/parsigex.go — direct n² broadcast of every
locally stored partial-signature set to all peers; incoming sets are
verified against the sending share's pubshares *before* storing
(parsigex.go:94-98). MemTransport is the in-process variant the simnet
uses (ref: core/parsigex/memory.go); the TCP transport plugs into the same
component via the p2p layer.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Awaitable, Callable

from charon_tpu import tbls
from charon_tpu.core.cryptosvc import PlaneOverloadError
from charon_tpu.core.deadline import LATE_FACTOR, SlotClock
from charon_tpu.core.eth2data import ParSignedData
from charon_tpu.core.types import Duty, DutyType, PubKey
from charon_tpu.eth2util.signing import ForkInfo

ExSub = Callable[[Duty, dict[PubKey, ParSignedData]], Awaitable[None]]


def _transient() -> tuple:
    """Network-ish error classes worth a deadline-bounded resend — the
    ONE classification, owned by app/retry (lazy: core must not import
    app at module load)."""
    from charon_tpu.app.retry import RETRYABLE

    return RETRYABLE


class DutyGater:
    """Rejects expired or far-future duties before any crypto runs
    (ref: core/parsigex/parsigex.go:81 wires core.NewDutyGater,
    core/gater.go:38-79): a peer flooding stale-slot sets must not reach
    the batch verifier — free DoS amplification on the crypto plane
    otherwise.

    Future bound is epoch-granular like the reference (duty epoch within
    allowed_future_epochs of current, gater.go:72-78); the stale bound
    (slot older than LATE_FACTOR, matching the Deadliner's expiry window,
    core/deadline.go:23-26) goes beyond the reference and is skipped for
    epoch-scale duty types (exits, builder registrations) whose slots
    legitimately lag."""

    ALLOWED_FUTURE_EPOCHS = 2  # ref: core/gater.go defaultAllowedFutureEpochs

    _EPOCH_SCALE = (DutyType.EXIT, DutyType.BUILDER_REGISTRATION)

    def __init__(
        self,
        clock: SlotClock,
        slots_per_epoch: int = 32,
        # wall clock by design: gating maps "now" onto the slot
        # timeline, which IS wall-clock (SlotClock genesis arithmetic)
        now: Callable[[], float] = time.time,  # lint: allow(monotonic-clock)
    ) -> None:
        self._clock = clock
        self._spe = slots_per_epoch
        self._now = now

    def __call__(self, duty: Duty) -> bool:
        if not isinstance(duty.type, DutyType) or duty.type == DutyType.UNKNOWN:
            return False
        current = self._clock.slot_at(self._now())
        if (
            duty.slot // self._spe
            > current // self._spe + self.ALLOWED_FUTURE_EPOCHS
        ):
            return False
        if duty.type in self._EPOCH_SCALE:
            return True
        return duty.slot >= current - LATE_FACTOR


class Eth2Verifier:
    """Verifies peer partial signatures against the sender's pubshares,
    batched (ref: core/parsigex/parsigex.go:146-170 NewEth2Verifier)."""

    def __init__(
        self,
        fork: ForkInfo,
        pubshares_by_idx: dict[int, dict[PubKey, bytes]],
        slots_per_epoch: int = 32,
        plane: object | None = None,  # core.cryptoplane.SlotCoalescer
        clock: SlotClock | None = None,  # duty deadlines for the plane
    ) -> None:
        self.fork = fork
        self.pubshares_by_idx = pubshares_by_idx
        self.slots_per_epoch = slots_per_epoch
        self.plane = plane
        self.clock = clock

    def _items(self, duty: Duty, signed_set: dict[PubKey, ParSignedData]):
        items = []
        for pubkey, psig in signed_set.items():
            shares = self.pubshares_by_idx.get(psig.share_idx)
            if shares is None or pubkey not in shares:
                return None
            root = psig.data.signing_root(
                self.fork, duty.slot // self.slots_per_epoch
            )
            items.append((shares[pubkey], root, psig.data.signature))
        return items

    def verify(self, duty: Duty, signed_set: dict[PubKey, ParSignedData]) -> bool:
        items = self._items(duty, signed_set)
        return items is not None and all(tbls.verify_batch(items))

    async def verify_async(
        self, duty: Duty, signed_set: dict[PubKey, ParSignedData]
    ) -> bool:
        """Plane path: inbound sets from all peers land within one
        coalescing window and verify as ONE sharded device program."""
        if self.plane is None:
            # plane-less rung: deliberately INLINE — the executor hop
            # GIL-convoys the busy loop and reorders inbound-set timing
            # (measured multi-x e2e slowdown); production wires the
            # plane. The overload-shed path below IS off-loop: it runs
            # exactly when the plane is saturated and the loop must
            # stay live.
            return self.verify(duty, signed_set)  # lint: allow(event-loop-blocking)
        items = self._items(duty, signed_set)
        if items is None:
            return False
        kwargs = {}
        if self.clock is not None:
            # near-deadline sets shrink the coalescing window instead of
            # waiting out a load-grown one (core/cryptoplane adaptive)
            kwargs["deadline"] = self.clock.duty_deadline(duty)
        try:
            return all(await self.plane.verify(items, **kwargs))
        except PlaneOverloadError:
            # admission shed (core/cryptosvc backpressure): serve THIS
            # set from the host tbls rung — on an executor thread, so
            # shed load costs latency on the degraded path, never a
            # dropped inbound set or a blocked event loop (host BLS is
            # ~0.3 s/verify on the python rung)
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None, self.verify, duty, signed_set
            )


class MemTransport:
    """Loopback wiring of n ParSigEx components (in-process simnet).

    Deliveries are isolated per destination (ref: p2p sender failures
    are per-peer): one receiver's downstream failure must neither skip
    the remaining peers nor cascade back into the sender's own duty
    pipeline."""

    def __init__(self) -> None:
        self.nodes: list["ParSigEx"] = []

    def attach(self, node: "ParSigEx") -> None:
        self.nodes.append(node)

    async def send(
        self, from_idx: int, duty: Duty, signed_set, tctx: str | None = None
    ) -> None:
        # loopback crosses a simulated network boundary: drop the
        # sender's ambient span context so trace propagation happens
        # ONLY through the frame's tctx, as it would over real sockets
        from charon_tpu.app.tracer import detached

        for node in self.nodes:
            if node.share_idx == from_idx:
                continue
            try:
                with detached():
                    await node.receive(
                        duty, signed_set, tctx=tctx, sender=from_idx
                    )
            except Exception as e:  # noqa: BLE001 — per-peer isolation
                from charon_tpu.app import log

                log.warn(
                    "peer receive failed",
                    topic="parsigex",
                    peer=node.share_idx,
                    duty=str(duty),
                    err=f"{type(e).__name__}: {e}",
                )


class ParSigEx:
    """clock (optional SlotClock): enables deadline-aware resend — a
    transient transport failure re-sends with jittered backoff until the
    duty's deadline instead of giving up after one attempt (reusing
    app/expbackoff; ref: p2p sender retries under the duty context)."""

    def __init__(
        self,
        share_idx: int,
        transport: MemTransport,
        verifier: Eth2Verifier | None = None,
        gater: Callable[[Duty], bool] | None = None,
        clock: SlotClock | None = None,
        tracer=None,  # app/tracer.Tracer; None = process-global
        evidence=None,  # core/evidence.EvidenceRegistry; None = unrecorded
    ) -> None:
        self.share_idx = share_idx
        self.transport = transport
        self.verifier = verifier
        self.gater = gater
        self.clock = clock
        self.tracer = tracer
        self.evidence = evidence
        self.dropped_stale = 0  # metric: sets gated before crypto
        self.dropped_spoofed = 0  # sets claiming another peer's share idx
        self.dropped_invalid = 0  # sets that failed signature verification
        self.resend_total = 0  # metric: deadline-retry resends
        self._subs: list[ExSub] = []
        self._retry_tasks: set = set()
        transport.attach(self)

    def subscribe(self, sub: ExSub) -> None:
        self._subs.append(sub)

    async def broadcast(self, duty: Duty, signed_set: dict[PubKey, ParSignedData]) -> None:
        """Send our partials to all peers (ref: parsigex.go:112).

        First attempt inline; on a transient transport failure the send
        moves to a background deadline-bounded retry (fire-and-forget,
        like the reference's SendAsync) so the VC's submission path is
        never held hostage by a flapping peer link. Receivers dedup by
        share index, so a resend that partially succeeded is safe.

        The frame carries the sender's trace context (ref: the reference
        propagates OTel context in its p2p envelopes), so the receiving
        node's spans join this duty trace under true parentage."""
        tctx = self._trace_ctx()
        try:
            await self.transport.send(
                self.share_idx, duty, signed_set, tctx=tctx
            )
        except _transient() as e:
            if self.clock is None:
                raise
            import asyncio

            from charon_tpu.app import log

            log.warn(
                "parsig send failed; retrying until duty deadline",
                topic="parsigex",
                duty=str(duty),
                err=f"{type(e).__name__}: {e}",
            )
            # anchor the wall duty deadline to the monotonic base HERE,
            # at failure time while the clock is still honest (the PR 8
            # _arm bug class) — the retry task then runs entirely on
            # monotonic, immune to host clock steps mid-backoff
            deadline_mono = time.monotonic() + (
                self.clock.duty_deadline(duty) - time.time()  # lint: allow(monotonic-clock) — one-shot wall->mono anchor
            )
            task = asyncio.create_task(
                self._resend(duty, signed_set, tctx, deadline_mono)
            )
            self._retry_tasks.add(task)
            task.add_done_callback(self._retry_tasks.discard)

    @staticmethod
    def _trace_ctx() -> str | None:
        from charon_tpu.app.tracer import encode_ctx

        return encode_ctx()

    async def _resend(
        self, duty: Duty, signed_set, tctx: str | None, deadline: float
    ) -> None:
        """`deadline` is MONOTONIC-base (anchored by broadcast at
        failure time), so the backoff loop below never reads the wall
        clock — a host clock step mid-retry can neither abort the
        remaining resends nor resend past expiry."""
        import asyncio

        from charon_tpu.app.expbackoff import FAST_CONFIG, backoff_delay

        attempt = 0
        while True:
            delay = backoff_delay(FAST_CONFIG, attempt)
            if time.monotonic() + delay >= deadline:
                return  # deadline exhausted; tracker reports the miss
            await asyncio.sleep(delay)
            attempt += 1
            try:
                await self.transport.send(
                    self.share_idx, duty, signed_set, tctx=tctx
                )
                self.resend_total += 1
                return
            except _transient():
                continue

    async def receive(
        self,
        duty: Duty,
        signed_set: dict[PubKey, ParSignedData],
        tctx: str | None = None,
        sender: int | None = None,
    ) -> None:
        """Peer partials arrive; gate, verify, then store
        (ref: parsigex.go:68-109). The gater runs *before* signature
        verification so stale floods never reach the batch verifier.

        `sender` is the CHANNEL identity — the authenticated share index
        the transport received this frame from (None for direct callers
        and legacy fakes). With it, two Byzantine detections attribute to
        the right peer: a set claiming a DIFFERENT share index than its
        channel is a spoof by the channel peer (dropped before any
        crypto — otherwise forged partials stamped with a victim's index
        would bill evidence to the victim), and a set that fails
        verification is billed to the channel that delivered it.

        `tctx` is the sender's propagated trace context: the receive
        span (and everything nested under it — verification, the
        store_external edge, threshold aggregation) joins the sender's
        duty trace. A corrupted/garbage tctx decodes to None and the
        span falls back to a fresh duty-rooted root — frame chaos must
        never crash the receive path."""
        from charon_tpu.app.tracer import parse_ctx, span

        if self.gater is not None and not self.gater(duty):
            self.dropped_stale += 1
            return
        if sender is not None and any(
            ps.share_idx != sender for ps in signed_set.values()
        ):
            self.dropped_spoofed += 1
            if self.evidence is not None:
                self.evidence.record(sender, "parsig_spoof")
            return
        with span(
            "parsigex.receive",
            duty=duty,
            tracer=self.tracer,
            remote=parse_ctx(tctx),
            pubkeys=len(signed_set),
        ):
            if self.verifier is not None:
                check = getattr(self.verifier, "verify_async", None)
                if check is not None:
                    ok = await check(duty, signed_set)
                else:
                    # duck-typed sync verifier (test fakes): inline on
                    # purpose, same rationale as verify_async's plane-
                    # less rung above
                    ok = self.verifier.verify(duty, signed_set)  # lint: allow(event-loop-blocking)
                if not ok:
                    # drop invalid sets; billed to the channel peer when
                    # known, else to the claimed share indices (the best
                    # identity a channel-less caller has)
                    self.dropped_invalid += 1
                    if self.evidence is not None:
                        peers = (
                            {sender}
                            if sender is not None
                            else {
                                ps.share_idx
                                for ps in signed_set.values()
                            }
                        )
                        for peer in peers:
                            self.evidence.record(peer, "parsig_invalid")
                    return
            for sub in self._subs:
                await sub(duty, signed_set)
