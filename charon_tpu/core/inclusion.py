"""Inclusion checker: did broadcast duties actually land on-chain?

Mirrors ref: core/tracker/inclusion.go — every submitted attestation,
aggregate and block proposal is tracked; for the next INCL_CHECK_LAG slots
the checker inspects each new block for the submission (attestation-data
root + covered aggregation bits for attestations, the block root itself
for proposals). Submissions found are reported included (with the
inclusion delay); submissions still pending after the lag are reported
missed. Wiring mirrors app/app.go:746-780: subscribes downstream of the
broadcaster and on the scheduler's slot ticks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Awaitable, Callable

from charon_tpu.core.types import Duty, DutyType, PubKey

# ref: core/tracker/inclusion.go InclCheckLag — a duty missing for 32
# slots after submission is declared missed.
INCL_CHECK_LAG = 32

# Duty types the checker can observe on-chain. Everything else (randao,
# selection proofs, exits, registrations) has no per-block footprint
# (ref: inclusion.go only tracks attestations/aggregates/blocks).
_TRACKED = (DutyType.ATTESTER, DutyType.AGGREGATOR, DutyType.PROPOSER)


@dataclass(frozen=True)
class InclusionReport:
    duty: Duty
    pubkey: PubKey
    included: bool
    delay_slots: int  # block slot - duty slot when included, else -1


ReportSub = Callable[[InclusionReport], Awaitable[None] | None]


@dataclass
class _Pending:
    duty: Duty
    pubkey: PubKey
    att_data_root: bytes | None  # attester/aggregator match key
    agg_bits: tuple[bool, ...]  # bits our submission covered
    block_root: bytes | None  # proposer match key


class InclusionChecker:
    """beacon duck-type requirements (provided by BeaconMock and the
    production client): `block_attestations(slot) -> list | None` (None =
    no block at that slot) and `block_root(slot) -> bytes | None`."""

    def __init__(self, beacon, on_report: ReportSub | None = None) -> None:
        self.beacon = beacon
        self._pending: list[_Pending] = []
        self._subs: list[ReportSub] = list(filter(None, [on_report]))
        self._checked_until: int | None = None
        self.included_total = 0
        self.missed_total = 0
        self.inclusion_delay_sum = 0

    def subscribe(self, sub: ReportSub) -> None:
        self._subs.append(sub)

    # -- intake: wire after broadcaster.broadcast -------------------------

    async def submitted(self, duty: Duty, data_set) -> None:
        """Record broadcast signed duties (ref: inclusion.go Submitted)."""
        if duty.type not in _TRACKED:
            return
        for pubkey, signed in data_set.items():
            att_root = None
            bits: tuple[bool, ...] = ()
            block_root = None
            payload = getattr(signed, "payload", signed)
            if duty.type == DutyType.ATTESTER:
                att_root = payload.data.hash_tree_root()
                bits = tuple(payload.aggregation_bits)
            elif duty.type == DutyType.AGGREGATOR:
                # payload is an AggregateAndProof carrying .aggregate
                agg = getattr(payload, "aggregate", payload)
                att_root = agg.data.hash_tree_root()
                bits = tuple(agg.aggregation_bits)
            elif duty.type == DutyType.PROPOSER:
                block_root = payload.hash_tree_root()
            self._pending.append(
                _Pending(
                    duty=duty,
                    pubkey=pubkey,
                    att_data_root=att_root,
                    agg_bits=bits,
                    block_root=block_root,
                )
            )

    # -- per-slot check: subscribe to scheduler slot ticks ----------------

    async def on_slot(self, slot) -> None:
        """Check blocks STRICTLY BEHIND the current slot (ref:
        inclusion.go trails the head by a lag for the same reason): at
        slot N's tick the slot-N duty has not broadcast yet, so block N
        is only inspected at the N+1 tick, after its submissions exist.
        Then expire submissions past the lag."""
        current = slot.slot
        if not self._pending:
            # idle: nothing to look for — skip the beacon round-trips
            # entirely rather than polling every slot forever
            self._checked_until = current - 1
            return
        start = self._checked_until
        if start is None:
            start = current - 2
        for s in range(start + 1, current):
            await self._check_block(s)
        self._checked_until = current - 1

        still = []
        for p in self._pending:
            if current - p.duty.slot > INCL_CHECK_LAG:
                await self._report(
                    InclusionReport(p.duty, p.pubkey, included=False, delay_slots=-1)
                )
                self.missed_total += 1
            else:
                still.append(p)
        self._pending = still

    async def _check_block(self, block_slot: int) -> None:
        # fetch only what the pending submissions actually need
        atts = (
            await self.beacon.block_attestations(block_slot)
            if any(p.att_data_root is not None for p in self._pending)
            else None
        )
        root = (
            await self.beacon.block_root(block_slot)
            if any(p.block_root is not None for p in self._pending)
            else None
        )
        if atts is None and root is None:
            return  # no block this slot
        by_root: dict[bytes, list] = {}
        for att in atts or []:
            by_root.setdefault(att.data.hash_tree_root(), []).append(att)

        still = []
        for p in self._pending:
            hit = False
            if p.att_data_root is not None:
                for att in by_root.get(p.att_data_root, []):
                    chain_bits = tuple(att.aggregation_bits)
                    ours = tuple(p.agg_bits)
                    if all(
                        not mine or (i < len(chain_bits) and chain_bits[i])
                        for i, mine in enumerate(ours)
                    ):
                        hit = True
                        break
            elif p.block_root is not None:
                hit = block_slot == p.duty.slot and root == p.block_root
            if hit:
                delay = block_slot - p.duty.slot
                self.included_total += 1
                self.inclusion_delay_sum += delay
                await self._report(
                    InclusionReport(p.duty, p.pubkey, included=True, delay_slots=delay)
                )
            else:
                still.append(p)
        self._pending = still

    async def _report(self, report: InclusionReport) -> None:
        for sub in self._subs:
            res = sub(report)
            if hasattr(res, "__await__"):
                await res
