"""Inclusion checker: did broadcast duties actually land on-chain?

Mirrors ref: core/tracker/inclusion.go — every submitted attestation,
aggregate and block proposal is tracked; the checker trails the head by
INCL_CHECK_LAG slots (reorg mitigation) and inspects each block for the
submission (attestation-data root + covered aggregation bits for
attestations, the block root itself for proposals). Submissions found
are reported included (with the inclusion delay); submissions still
pending after INCL_MISSED_LAG slots are reported missed. Synthetic
proposals (fabricated by the SyntheticProposer wrapper and swallowed at
submit) are reported included immediately — they have no on-chain
footprint and must not surface as false misses (ref: inclusion.go:80
Submitted's IsSyntheticProposal branch). Wiring mirrors
app/app.go:746-780: subscribes downstream of the broadcaster and on the
scheduler's slot ticks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Awaitable, Callable

from charon_tpu.core.types import Duty, DutyType, PubKey

# ref: core/tracker/inclusion.go:28 InclCheckLag — blocks are inspected
# only once they are this many slots deep, so a short reorg cannot make
# the checker mis-report (6 covers almost all PoS reorgs).
INCL_CHECK_LAG = 6

# ref: core/tracker/inclusion.go:33 InclMissedLag — a duty still pending
# this many slots after its slot is declared missed and dropped.
INCL_MISSED_LAG = 32

# Duty types the checker can observe on-chain. Everything else (randao,
# selection proofs, exits, registrations) has no per-block footprint
# (ref: inclusion.go only tracks attestations/aggregates/blocks).
_TRACKED = (DutyType.ATTESTER, DutyType.AGGREGATOR, DutyType.PROPOSER)


@dataclass(frozen=True)
class InclusionReport:
    duty: Duty
    pubkey: PubKey
    included: bool
    delay_slots: int  # block slot - duty slot when included, else -1
    # seconds from slot start to broadcast, when a clock was provided
    # (ref: inclusion.go submission.Delay in every report log line)
    broadcast_delay: float | None = None
    # fabricated duty with no on-chain footprint, reported included at
    # submit time (ref: inclusion.go Submitted synthetic branch)
    synthetic: bool = False


ReportSub = Callable[[InclusionReport], Awaitable[None] | None]


@dataclass
class _Pending:
    duty: Duty
    pubkey: PubKey
    att_data_root: bytes | None  # attester/aggregator match key
    agg_bits: tuple[bool, ...]  # bits our submission covered
    block_root: bytes | None  # proposer match key
    broadcast_delay: float | None = None


def _is_synthetic_block(payload) -> bool:
    """Fabricated proposal from the SyntheticProposer wrapper — detected
    structurally (the wrapper tags dict proposals) so core never imports
    app (ref: app/eth2wrap/synthproposer.go marks via graffiti)."""
    if isinstance(payload, dict):
        return bool(payload.get("synthetic"))
    return bool(getattr(payload, "synthetic", False))


class InclusionChecker:
    """beacon duck-type requirements (provided by BeaconMock and the
    production client): `block_attestations(slot) -> list | None` (None =
    no block at that slot) and `block_root(slot) -> bytes | None`.

    `check_lag`/`missed_lag` default to the reference's production
    constants; tests shrink them to drive scenarios quickly. `clock`
    (optional, `slot_start(slot) -> epoch seconds`) stamps each report
    with the broadcast delay."""

    def __init__(
        self,
        beacon,
        on_report: ReportSub | None = None,
        check_lag: int = INCL_CHECK_LAG,
        missed_lag: int = INCL_MISSED_LAG,
        clock=None,
    ) -> None:
        self.beacon = beacon
        self.check_lag = check_lag
        self.missed_lag = missed_lag
        self.clock = clock
        self._pending: list[_Pending] = []
        self._subs: list[ReportSub] = list(filter(None, [on_report]))
        self._checked_until: int | None = None
        self.included_total = 0
        self.missed_total = 0
        self.inclusion_delay_sum = 0

    def subscribe(self, sub: ReportSub) -> None:
        self._subs.append(sub)

    # -- intake: wire after broadcaster.broadcast -------------------------

    async def submitted(self, duty: Duty, data_set) -> None:
        """Record broadcast signed duties (ref: inclusion.go Submitted)."""
        if duty.type not in _TRACKED:
            return
        delay = None
        if self.clock is not None:
            import time as _time

            # attribution edge: inclusion delay vs the slot's wall-clock
            # start — both terms live on the wall timeline
            delay = _time.time() - self.clock.slot_start(duty.slot)  # lint: allow(monotonic-clock)
        for pubkey, signed in data_set.items():
            att_root = None
            bits: tuple[bool, ...] = ()
            block_root = None
            payload = getattr(signed, "payload", signed)
            if duty.type == DutyType.ATTESTER:
                att_root = payload.data.hash_tree_root()
                bits = tuple(payload.aggregation_bits)
            elif duty.type == DutyType.AGGREGATOR:
                # payload is an AggregateAndProof carrying .aggregate
                agg = getattr(payload, "aggregate", payload)
                att_root = agg.data.hash_tree_root()
                bits = tuple(agg.aggregation_bits)
            elif duty.type == DutyType.PROPOSER:
                if _is_synthetic_block(payload):
                    # swallowed at submit, never on-chain: report
                    # included NOW or it would surface as a false miss
                    # 32 slots later (ref: inclusion.go:80 Submitted)
                    self.included_total += 1
                    await self._report(
                        InclusionReport(
                            duty,
                            pubkey,
                            included=True,
                            delay_slots=0,
                            broadcast_delay=delay,
                            synthetic=True,
                        )
                    )
                    continue
                block_root = payload.hash_tree_root()
            self._pending.append(
                _Pending(
                    duty=duty,
                    pubkey=pubkey,
                    att_data_root=att_root,
                    agg_bits=bits,
                    block_root=block_root,
                    broadcast_delay=delay,
                )
            )

    # -- per-slot check: subscribe to scheduler slot ticks ----------------

    async def on_slot(self, slot) -> None:
        """Check blocks trailing the current slot by `check_lag` (reorg
        mitigation, ref: inclusion.go:28 and its Run loop checking slot
        head-lag each tick): at slot N's tick the newest block inspected
        is N - check_lag, by which point the slot-N duty's submissions
        exist and short reorgs have settled. Then expire submissions
        past `missed_lag`."""
        current = slot.slot
        newest = current - self.check_lag
        if not self._pending:
            # idle: nothing to look for — skip the beacon round-trips
            # entirely rather than polling every slot forever
            self._checked_until = newest
            return
        start = self._checked_until
        if start is None:
            start = newest - 1
        for s in range(start + 1, newest + 1):
            await self._check_block(s)
        self._checked_until = max(start, newest)

        still = []
        for p in self._pending:
            # expire against the CHECKED frontier, not the head: blocks
            # are only inspected up to `newest`, so expiring at
            # head - missed_lag would falsely miss inclusions landing in
            # the last check_lag slots of the window
            if newest - p.duty.slot > self.missed_lag:
                await self._report(
                    InclusionReport(
                        p.duty,
                        p.pubkey,
                        included=False,
                        delay_slots=-1,
                        broadcast_delay=p.broadcast_delay,
                    )
                )
                self.missed_total += 1
            else:
                still.append(p)
        self._pending = still

    async def _check_block(self, block_slot: int) -> None:
        # fetch only what the pending submissions actually need
        atts = (
            await self.beacon.block_attestations(block_slot)
            if any(p.att_data_root is not None for p in self._pending)
            else None
        )
        root = (
            await self.beacon.block_root(block_slot)
            if any(p.block_root is not None for p in self._pending)
            else None
        )
        if atts is None and root is None:
            return  # no block this slot
        by_root: dict[bytes, list] = {}
        for att in atts or []:
            by_root.setdefault(att.data.hash_tree_root(), []).append(att)

        still = []
        for p in self._pending:
            hit = False
            if p.att_data_root is not None:
                for att in by_root.get(p.att_data_root, []):
                    chain_bits = tuple(att.aggregation_bits)
                    ours = tuple(p.agg_bits)
                    if all(
                        not mine or (i < len(chain_bits) and chain_bits[i])
                        for i, mine in enumerate(ours)
                    ):
                        hit = True
                        break
            elif p.block_root is not None:
                hit = block_slot == p.duty.slot and root == p.block_root
            if hit:
                delay = block_slot - p.duty.slot
                self.included_total += 1
                self.inclusion_delay_sum += delay
                await self._report(
                    InclusionReport(
                        p.duty,
                        p.pubkey,
                        included=True,
                        delay_slots=delay,
                        broadcast_delay=p.broadcast_delay,
                    )
                )
            else:
                still.append(p)
        self._pending = still

    async def _report(self, report: InclusionReport) -> None:
        for sub in self._subs:
            res = sub(report)
            if hasattr(res, "__await__"):
                await res
