"""Socket server exposing `CryptoPlaneService` to remote tenants.

The networked half of ROADMAP item 2's "crypto plane as a service": N
physically separate DV clusters dial ONE shared device mesh. The server
is a thin, failure-first adapter — every policy decision (EDF fairness,
admission, breaker quarantine) stays in `core/cryptosvc`; this module
only moves frames:

  * **accept** — send a fresh `CryptoChallenge` nonce, require a
    `CryptoHello` whose HMAC proof matches the tenant's configured
    token (`cryptosvc_wire.proof_ok`, constant-time). Auth failures get
    a generic ack and a closed socket: the error string never says
    whether the tenant id or the proof was wrong, and the token itself
    never appears anywhere — not on the wire, not in logs, not in
    metrics labels (secret-flow lint enforces this).
  * **submit** — `CryptoSubmit` maps onto `svc.submit(...)` with the
    relative deadline rebased onto this host's wall clock.
    `PlaneOverloadError` becomes a typed `CryptoShed` frame;
    `TblsError` rides back as a "tbls" result (a crypto VERDICT the
    client must not retry locally); any other exception as an "error"
    result (infrastructure — the client's local ladder takes over).
  * **attribution** — the server chains onto the shared coalescer's
    `stats_hook` and forwards each tenant's slice of every
    `FlushStats` as a compact dict on that tenant's next result frame
    (stage spans as offsets-back-from-send, so client-side rebasing
    needs no cross-host clock agreement).
  * **malformed frames** — per-frame drop-and-count through
    `p2p/quarantine.PeerQuarantine` (clients are NOT exempt here: a
    tenant streaming garbage gets its connection closed once muted).

The module is deliberately free of `jax` and `cryptography` imports so
a CPU-only image can serve (SimPlane-backed) and the chaos tier can
drive it everywhere.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time

from charon_tpu.core.cryptosvc import PlaneOverloadError
from charon_tpu.core.cryptosvc_wire import (
    HELLO_TIMEOUT,
    WIRE_VERSION,
    CryptoChallenge,
    CryptoHeartbeat,
    CryptoHello,
    CryptoHelloAck,
    CryptoResult,
    CryptoShed,
    CryptoSubmit,
    read_frame,
    send_frame,
)
from charon_tpu.p2p.codec import CodecError
from charon_tpu.p2p.quarantine import PeerQuarantine
from charon_tpu.tbls import TblsError

# pending per-tenant stats briefs are bounded: a tenant that stops
# submitting must not accumulate attribution dicts forever
_MAX_PENDING_STATS = 8


def _flush_brief(stats, now: float) -> dict:
    """Compact cross-process projection of one FlushStats: counters
    verbatim, stage spans as [start_back, end_back] offsets from `now`
    (the server's send instant) — the client rebases onto its own wall
    clock, so skewed hosts still get truthful span DURATIONS."""

    def rel(span):
        if not span:
            return None
        return [now - span[0], now - span[1]]

    return {
        "jobs": stats.jobs,
        "lanes": stats.lanes,
        "flush_seconds": stats.flush_seconds,
        "window": stats.window,
        "inflight": stats.inflight,
        "fallback": stats.fallback,
        "decode_mode": stats.decode_mode,
        "pack_rel": rel(stats.pack_span),
        "device_rel": rel(stats.device_span),
    }


class CryptoServiceServer:
    """Serves one `CryptoPlaneService` on a TCP port.

    auth_tokens: {tenant_id: token str|bytes}. Tenants must already be
    registered on the service (or pass `register_tenants=True` to have
    the server register them with default quotas on start).
    """

    def __init__(
        self,
        svc,
        auth_tokens: dict,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        heartbeat: float = 1.0,
        hello_timeout: float = HELLO_TIMEOUT,
        observer=None,  # callable(kind, tenant, **fields)
        quarantine: PeerQuarantine | None = None,
        register_tenants: bool = False,
    ) -> None:
        self._svc = svc
        self._auth_tokens = {
            tid: tok.encode() if isinstance(tok, str) else bytes(tok)
            for tid, tok in auth_tokens.items()
        }
        self.host = host
        self.port = port
        self.heartbeat = heartbeat
        self._hello_timeout = hello_timeout
        self.observer = observer
        self.quarantine = quarantine or PeerQuarantine()
        self._register_tenants = register_tenants
        self._server: asyncio.base_events.Server | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self._writers: set[asyncio.StreamWriter] = set()
        # tenant -> pending stats briefs; appended from the coalescer's
        # device worker THREAD, drained on the event loop — lock, not loop
        self._pending_stats: dict[str, list[dict]] = {}
        self._stats_mu = threading.Lock()
        self._stats_hook_installed = False
        self.served_jobs = 0
        self.auth_failures = 0

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._register_tenants:
            for tid in self._auth_tokens:
                if tid not in getattr(self._svc, "_tenants", {}):
                    self._svc.register(tid)
        self._install_stats_hook()
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        """Graceful stop: close the listener and every connection."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for w in list(self._writers):
            w.close()
        for t in list(self._conn_tasks):
            t.cancel()
        await asyncio.gather(*self._conn_tasks, return_exceptions=True)

    def abort(self) -> None:
        """SIGKILL stand-in for chaos scenarios: drop every connection
        transport without goodbye frames and stop accepting."""
        if self._server is not None:
            self._server.close()
            self._server = None
        for w in list(self._writers):
            transport = w.transport
            if transport is not None:
                transport.abort()
        for t in list(self._conn_tasks):
            t.cancel()

    # -- stats attribution -------------------------------------------------

    def _install_stats_hook(self) -> None:
        coal = getattr(self._svc, "coalescer", None)
        if coal is None or self._stats_hook_installed:
            return
        inner = getattr(coal, "stats_hook", None)

        def hook(stats, _inner=inner):
            self._on_flush_stats(stats)
            if _inner is not None:
                _inner(stats)

        coal.stats_hook = hook
        self._stats_hook_installed = True

    def _on_flush_stats(self, stats) -> None:
        """Runs on the coalescer's device worker thread."""
        tenant_lanes = getattr(stats, "tenant_lanes", ()) or ()
        if not tenant_lanes:
            return
        now = time.time()  # lint: allow(monotonic-clock) — attribution spans are wall-timestamped
        brief = _flush_brief(stats, now)
        with self._stats_mu:
            for tenant, lanes in tenant_lanes:
                per = dict(brief)
                per["tenant_lanes"] = lanes
                per["_captured"] = now
                q = self._pending_stats.setdefault(tenant, [])
                q.append(per)
                del q[:-_MAX_PENDING_STATS]

    def _pop_stats(self, tenant: str) -> dict | None:
        with self._stats_mu:
            q = self._pending_stats.get(tenant)
            if not q:
                return None
            brief = q.pop(0)
        # the span offsets were taken at capture; age them to THIS send
        age = time.time() - brief.pop("_captured", time.time())  # lint: allow(monotonic-clock)
        if age > 0:
            for key in ("pack_rel", "device_rel"):
                if brief.get(key):
                    brief[key] = [x + age for x in brief[key]]
        return brief

    def _observe(self, kind: str, tenant: str, **fields) -> None:
        if self.observer is not None:
            try:
                self.observer(kind, tenant, **fields)
            except Exception:  # noqa: BLE001 — observer bugs stay out
                pass

    # -- connection handling ----------------------------------------------

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        self._writers.add(writer)
        try:
            await self._serve_conn(reader, writer)
        except (
            ConnectionError,
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            CodecError,
            OSError,
        ):
            pass  # per-connection faults never take the server down
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _serve_conn(self, reader, writer) -> None:
        peername = writer.get_extra_info("peername") or ("?", 0)
        peer = f"{peername[0]}:{peername[1]}"
        # nonce is public by construction (the proof is what's secret)
        nonce = os.urandom(32)
        send_frame(writer, CryptoChallenge(nonce, WIRE_VERSION), False)
        await writer.drain()
        hello = await asyncio.wait_for(
            read_frame(reader), self._hello_timeout
        )
        if not isinstance(hello, CryptoHello):
            raise CodecError("expected CryptoHello")
        from charon_tpu.core.cryptosvc_wire import proof_ok

        auth_token = self._auth_tokens.get(hello.tenant_id)
        if auth_token is None or not proof_ok(
            auth_token, nonce, hello.proof
        ):
            # deliberately generic: no unknown-tenant vs bad-proof oracle
            self.auth_failures += 1
            self._observe("auth_fail", hello.tenant_id)
            send_frame(
                writer,
                CryptoHelloAck(ok=False, error="authentication failed"),
                False,
            )
            await writer.drain()
            return
        tenant_id = hello.tenant_id
        wire = min(WIRE_VERSION, hello.wire)
        binary = wire >= 1
        send_frame(
            writer,
            CryptoHelloAck(
                ok=True,
                wire=wire,
                t=self._svc.t,
                heartbeat=self.heartbeat,
            ),
            False,
        )
        await writer.drain()
        self._observe("connect", tenant_id, wire=wire)
        job_tasks: set[asyncio.Task] = set()
        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except CodecError:
                    # malformed payload inside an intact length-prefixed
                    # frame: drop-and-count, mute streams of garbage
                    self.quarantine.strike(peer)
                    if self.quarantine.muted(peer):
                        self._observe("quarantine", tenant_id)
                        return
                    continue
                self.quarantine.forgive(peer)
                if isinstance(msg, CryptoHeartbeat):
                    send_frame(
                        writer,
                        CryptoHeartbeat(msg.seq, echo=True),
                        binary,
                    )
                    await writer.drain()
                elif isinstance(msg, CryptoSubmit):
                    t = asyncio.create_task(
                        self._run_job(writer, tenant_id, msg, binary)
                    )
                    job_tasks.add(t)
                    t.add_done_callback(job_tasks.discard)
                # unknown-but-valid frames: ignore (forward compat)
        finally:
            for t in job_tasks:
                t.cancel()
            self._observe("disconnect", tenant_id)

    async def _run_job(
        self, writer, tenant_id: str, msg: CryptoSubmit, binary: bool
    ) -> None:
        deadline = (
            None
            if msg.deadline_rel is None
            # svc.submit deadlines are wall-clock by plane contract;
            # rebasing the relative remainder here needs no cross-host
            # clock agreement
            else time.time() + msg.deadline_rel  # lint: allow(monotonic-clock)
        )
        try:
            try:
                value = await self._svc.submit(
                    tenant_id, msg.kind, tuple(msg.args), msg.lanes,
                    deadline,
                )
            except PlaneOverloadError as e:
                self._observe(
                    "shed", tenant_id, reason=e.reason, lanes=msg.lanes
                )
                send_frame(
                    writer, CryptoShed(msg.job_id, e.reason), binary
                )
            except TblsError as e:
                send_frame(
                    writer,
                    CryptoResult(
                        msg.job_id,
                        error=str(e)[:200],
                        error_kind="tbls",
                    ),
                    binary,
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — surfaced to client
                send_frame(
                    writer,
                    CryptoResult(
                        msg.job_id,
                        error=f"{type(e).__name__}: {str(e)[:200]}",
                        error_kind="error",
                    ),
                    binary,
                )
            else:
                self.served_jobs += 1
                send_frame(
                    writer,
                    CryptoResult(
                        msg.job_id,
                        value=value,
                        stats=self._pop_stats(tenant_id),
                    ),
                    binary,
                )
            await writer.drain()
        except (ConnectionError, OSError):
            pass  # client went away; its local ladder owns the job now
