"""AggSigDB: store of aggregated (group) signatures for later queries.

Two implementations behind the AGG_SIG_DB_V2 feature flag, mirroring the
reference's rollout pair (ref: core/aggsigdb/memory.go command-loop
design as the default, memory_v2.go simpler-locking design behind
app/featureset/featureset.go:56 AggSigDBV2, selected at wiring time in
app/app.go) — randao reveals are awaited by the proposal fetcher,
selection proofs by the aggregator fetcher. Both are trimmed by the
Deadliner and fail outstanding waiters at duty expiry.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict

from charon_tpu.core.eth2data import SignedData
from charon_tpu.core.types import Duty, PubKey


class DutyExpiredError(Exception):
    """The duty's deadline passed before its aggregate arrived."""


def new_agg_sigdb():
    """Implementation selected by the AGG_SIG_DB_V2 feature flag
    (ref: app wiring picks memory_v2 only when the alpha flag is on)."""
    from charon_tpu.app import featureset

    if featureset.enabled(featureset.Feature.AGG_SIG_DB_V2):
        return AggSigDBV2()
    return AggSigDBLoop()


class AggSigDBV2:
    def __init__(self) -> None:
        self._values: dict[tuple[Duty, PubKey], SignedData] = {}
        self._waiters: dict[tuple[Duty, PubKey], list[asyncio.Future]] = (
            defaultdict(list)
        )

    async def store(self, duty: Duty, pubkey: PubKey, data: SignedData) -> None:
        key = (duty, pubkey)
        prev = self._values.get(key)
        if prev is not None:
            if prev.signature != data.signature:
                raise ValueError(f"conflicting aggregate for {key}")
            return
        self._values[key] = data
        for fut in self._waiters.pop(key, []):
            if not fut.done():
                fut.set_result(data)

    async def store_set(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        for pubkey, data in data_set.items():
            await self.store(duty, pubkey, data)

    async def await_(self, duty: Duty, pubkey: PubKey) -> SignedData:
        key = (duty, pubkey)
        if key in self._values:
            return self._values[key]
        fut = asyncio.get_running_loop().create_future()
        self._waiters[key].append(fut)
        return await fut

    def trim(self, expired: Duty) -> None:
        """Drop stored values AND fail outstanding waiters for the duty:
        a VC request awaiting an aggregate that never formed must error
        at duty expiry, not hang until its HTTP timeout (ref: the
        deadliner trim path errors queued queries, memory_v2.go)."""
        self._values = {
            k: v for k, v in self._values.items() if k[0] != expired
        }
        for key in [k for k in self._waiters if k[0] == expired]:
            for fut in self._waiters.pop(key, []):
                if not fut.done():
                    fut.set_exception(
                        DutyExpiredError(
                            f"duty expired before aggregate arrived: {key[0]}"
                        )
                    )


class AggSigDBLoop:
    """Command-loop variant: every mutation and query is a command
    consumed by ONE actor task, so state is touched from a single
    coroutine and blocked queries are parked and retried after each
    write (ref: core/aggsigdb/memory.go — the original
    channel-serialized design; our actor task is the asyncio analogue
    of its run() goroutine + command channels).

    Same API and semantics as AggSigDBV2: identical-store idempotence,
    ValueError on a conflicting aggregate, DutyExpiredError for waiters
    of a trimmed duty."""

    def __init__(self) -> None:
        self._cmds: asyncio.Queue = asyncio.Queue()
        self._values: dict[tuple[Duty, PubKey], SignedData] = {}
        # parked queries awaiting a value: key -> futures
        self._parked: dict[tuple[Duty, PubKey], list[asyncio.Future]] = (
            defaultdict(list)
        )
        self._task: asyncio.Task | None = None

    def _ensure_loop(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="aggsigdb-loop"
            )

    async def _run(self) -> None:
        while True:
            op, *args = await self._cmds.get()
            if op == "store":
                key, data, done = args
                prev = self._values.get(key)
                if prev is not None:
                    # done() guards: a timed-out caller may have
                    # cancelled its ack future while the command sat in
                    # the queue — resolving it would InvalidStateError
                    # and kill the actor task
                    if prev.signature != data.signature:
                        if not done.done():
                            done.set_exception(
                                ValueError(
                                    f"conflicting aggregate for {key}"
                                )
                            )
                    elif not done.done():
                        done.set_result(None)
                    continue
                self._values[key] = data
                for fut in self._parked.pop(key, []):
                    if not fut.done():
                        fut.set_result(data)
                if not done.done():
                    done.set_result(None)
            elif op == "query":
                key, fut = args
                value = self._values.get(key)
                if value is not None:
                    if not fut.done():  # caller may have timed out
                        fut.set_result(value)
                else:
                    self._parked[key].append(fut)
            elif op == "trim":
                (expired,) = args
                self._values = {
                    k: v for k, v in self._values.items() if k[0] != expired
                }
                for key in [k for k in self._parked if k[0] == expired]:
                    for fut in self._parked.pop(key, []):
                        if not fut.done():
                            fut.set_exception(
                                DutyExpiredError(
                                    "duty expired before aggregate "
                                    f"arrived: {key[0]}"
                                )
                            )

    async def store(self, duty: Duty, pubkey: PubKey, data: SignedData) -> None:
        self._ensure_loop()
        done = asyncio.get_running_loop().create_future()
        self._cmds.put_nowait(("store", (duty, pubkey), data, done))
        await done

    async def store_set(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        for pubkey, data in data_set.items():
            await self.store(duty, pubkey, data)

    async def await_(self, duty: Duty, pubkey: PubKey) -> SignedData:
        self._ensure_loop()
        fut = asyncio.get_running_loop().create_future()
        self._cmds.put_nowait(("query", (duty, pubkey), fut))
        return await fut

    def trim(self, expired: Duty) -> None:
        # Deadliner hook runs inside the event loop, so the actor task
        # exists whenever there is anything to trim; a pre-loop trim is
        # a no-op on empty state.
        self._cmds.put_nowait(("trim", expired))
        if self._task is None or self._task.done():
            try:
                self._ensure_loop()
            except RuntimeError:
                pass  # no running loop: nothing stored yet either


# Historical name: the mutex/keyed-futures design was this framework's
# first (and only) implementation through round 4.
AggSigDB = AggSigDBV2
