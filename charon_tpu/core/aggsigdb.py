"""AggSigDB: store of aggregated (group) signatures for later queries.

Mirrors ref: core/aggsigdb/memory_v2.go (the simpler mutex design behind
the AggSigDBV2 feature flag) — randao reveals are awaited by the proposal
fetcher, selection proofs by the aggregator fetcher. Blocking awaits via
keyed futures, trimmed by the Deadliner.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict

from charon_tpu.core.eth2data import SignedData
from charon_tpu.core.types import Duty, PubKey


class DutyExpiredError(Exception):
    """The duty's deadline passed before its aggregate arrived."""


class AggSigDB:
    def __init__(self) -> None:
        self._values: dict[tuple[Duty, PubKey], SignedData] = {}
        self._waiters: dict[tuple[Duty, PubKey], list[asyncio.Future]] = (
            defaultdict(list)
        )

    async def store(self, duty: Duty, pubkey: PubKey, data: SignedData) -> None:
        key = (duty, pubkey)
        prev = self._values.get(key)
        if prev is not None:
            if prev.signature != data.signature:
                raise ValueError(f"conflicting aggregate for {key}")
            return
        self._values[key] = data
        for fut in self._waiters.pop(key, []):
            if not fut.done():
                fut.set_result(data)

    async def store_set(self, duty: Duty, data_set: dict[PubKey, SignedData]) -> None:
        for pubkey, data in data_set.items():
            await self.store(duty, pubkey, data)

    async def await_(self, duty: Duty, pubkey: PubKey) -> SignedData:
        key = (duty, pubkey)
        if key in self._values:
            return self._values[key]
        fut = asyncio.get_running_loop().create_future()
        self._waiters[key].append(fut)
        return await fut

    def trim(self, expired: Duty) -> None:
        """Drop stored values AND fail outstanding waiters for the duty:
        a VC request awaiting an aggregate that never formed must error
        at duty expiry, not hang until its HTTP timeout (ref: the
        deadliner trim path errors queued queries, memory_v2.go)."""
        self._values = {
            k: v for k, v in self._values.items() if k[0] != expired
        }
        for key in [k for k in self._waiters if k[0] == expired]:
            for fut in self._waiters.pop(key, []):
                if not fut.done():
                    fut.set_exception(
                        DutyExpiredError(
                            f"duty expired before aggregate arrived: {key[0]}"
                        )
                    )
