"""HTTP router for the ValidatorAPI: the eth2 beacon API served to VCs.

Mirrors ref: core/validatorapi/router.go:97-253 — the full intercepted
endpoint set served locally with blocking awaits:

  attester:    attestation_data, submit attestations
  proposer:    v3 blocks (randao partial via query param), submit
               (blinded) blocks
  aggregator:  beacon-committee selections (partials in, aggregated out),
               aggregate_attestation, aggregate_and_proofs
  sync:        sync duties, sync-committee messages, sync-committee
               selections, contribution, contribution_and_proofs
  lifecycle:   validators (pubshare <-> group pubkey mapping), duties
               (attester/proposer/sync), registrations, voluntary exit,
               prepare_beacon_proposer, subscriptions, genesis/spec/fork,
               node version/health/syncing

Everything else 404s with a clear error (the reference proxies unknown
routes to the upstream BN, router.go proxyHandler; the simnet beacon mock
serves no extra routes worth proxying).

JSON schema follows the eth2 beacon API shapes for the implemented
endpoints (integers as strings, 0x-hex byte fields).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from aiohttp import web

from charon_tpu.core.eth2data import (
    AggregateAndProof,
    Attestation,
    AttestationData,
    Checkpoint,
    ContributionAndProof,
    Proposal,
    SyncCommitteeContribution,
    SyncCommitteeMessage,
    ValidatorRegistration,
    VoluntaryExit,
    proposal_data_json,
    proposal_data_ssz,
    signed_proposal_from_json,
    signed_proposal_from_ssz,
)
from charon_tpu.core.types import Duty, DutyType, PubKey
from charon_tpu.core.validatorapi import ValidatorAPI, VapiError
from charon_tpu.eth2util import spec

# ---------------------------------------------------------------------------
# JSON codecs (eth2 beacon API shapes)
# ---------------------------------------------------------------------------


def _hex(b: bytes) -> str:
    return "0x" + b.hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


# One SSZ-bitfield/attestation JSON codec exists: the descriptor-driven
# one in eth2util/spec.py. The wrappers below keep the local call sites
# and legacy signatures (proposal shapes live in core/eth2data.py).


def _att_data_json(d: AttestationData) -> dict:
    return spec.to_json(d)


def _att_data_from_json(j: dict) -> AttestationData:
    return spec.from_json(AttestationData, j)


def _bits_from_hex(hexstr: str) -> tuple[bool, ...]:
    return spec.bits_from_bytes(_unhex(hexstr), sentinel=True)


def _bits_to_hex(bits: tuple[bool, ...]) -> str:
    return "0x" + spec.bits_to_bytes(bits, sentinel=True).hex()


def _bitvector_to_hex(bits: tuple[bool, ...], size: int = 128) -> str:
    full = tuple(bits) + (False,) * (size - len(bits))
    return "0x" + spec.bits_to_bytes(full[:size], sentinel=False).hex()


def _bitvector_from_hex(hexstr: str, size: int = 128) -> tuple[bool, ...]:
    return spec.bits_from_bytes(_unhex(hexstr), sentinel=False, length=size)


def _attestation_json(a: Attestation) -> dict:
    return spec.to_json(a)


def _attestation_from_json(j: dict) -> Attestation:
    return spec.from_json(Attestation, j)


def _contribution_json(c: SyncCommitteeContribution) -> dict:
    return {
        "slot": str(c.slot),
        "beacon_block_root": _hex(c.beacon_block_root),
        "subcommittee_index": str(c.subcommittee_index),
        "aggregation_bits": _bitvector_to_hex(c.aggregation_bits),
        "signature": _hex(c.signature),
    }


def _contribution_from_json(j: dict) -> SyncCommitteeContribution:
    return SyncCommitteeContribution(
        slot=int(j["slot"]),
        beacon_block_root=_unhex(j["beacon_block_root"]),
        subcommittee_index=int(j["subcommittee_index"]),
        aggregation_bits=_bitvector_from_hex(j["aggregation_bits"]),
        signature=_unhex(j["signature"]),
    )


def _err(status: int, message: str) -> web.Response:
    return web.json_response({"code": status, "message": message}, status=status)


class VapiRouter:
    """vapi: the transport-agnostic component; beacon: duck-typed client
    for duties resolution; validators: group pubkey -> validator index."""

    def __init__(
        self,
        vapi: ValidatorAPI,
        beacon=None,
        validators: dict[PubKey, int] | None = None,
        genesis_time: float = 0.0,
        slots_per_epoch: int = 32,
        slot_duration: float = 12.0,
        clock=None,
    ) -> None:
        from charon_tpu.core.deadline import SlotClock

        self.vapi = vapi
        self.beacon = beacon
        self.validators = validators or {}
        self.genesis_time = genesis_time
        self.slots_per_epoch = slots_per_epoch
        self.slot_duration = slot_duration
        self.clock = clock or SlotClock(genesis_time, max(slot_duration, 1e-9))
        # pubshare (this node's) -> group pubkey, for VC keystore lookups
        # (ref: validatorapi.go:1080,1167 pubshare<->group mapping)
        self._group_by_pubshare = {
            "0x" + ps.hex(): gpk for gpk, ps in vapi.pubshares.items()
        }
        self._pubkey_by_index = {
            i: pk for pk, i in self.validators.items()
        }
        self.app = web.Application()
        self.app.add_routes(
            [
                # attester (ref: router.go:115,121)
                web.get("/eth/v1/validator/attestation_data", self._attestation_data),
                web.post("/eth/v1/beacon/pool/attestations", self._submit_attestations),
                web.post("/eth/v2/beacon/pool/attestations", self._submit_attestations),
                # proposer (ref: router.go:151,157-175)
                web.get("/eth/v3/validator/blocks/{slot}", self._produce_block_v3),
                web.post("/eth/v1/beacon/blocks", self._submit_block),
                web.post("/eth/v2/beacon/blocks", self._submit_block),
                web.post("/eth/v1/beacon/blinded_blocks", self._submit_block),
                web.post("/eth/v2/beacon/blinded_blocks", self._submit_block),
                # aggregator (ref: router.go:127-145, validatorapi.go:724)
                web.post(
                    "/eth/v1/validator/beacon_committee_selections",
                    self._beacon_committee_selections,
                ),
                web.get(
                    "/eth/v1/validator/aggregate_attestation",
                    self._aggregate_attestation,
                ),
                web.get(
                    "/eth/v2/validator/aggregate_attestation",
                    self._aggregate_attestation,
                ),
                web.post(
                    "/eth/v1/validator/aggregate_and_proofs",
                    self._aggregate_and_proofs,
                ),
                web.post(
                    "/eth/v2/validator/aggregate_and_proofs",
                    self._aggregate_and_proofs,
                ),
                # sync committee (ref: router.go:181-205)
                web.post("/eth/v1/beacon/pool/sync_committees", self._submit_sync_messages),
                web.post(
                    "/eth/v1/validator/sync_committee_selections",
                    self._sync_committee_selections,
                ),
                web.get(
                    "/eth/v1/validator/sync_committee_contribution",
                    self._sync_contribution,
                ),
                web.post(
                    "/eth/v1/validator/contribution_and_proofs",
                    self._contribution_and_proofs,
                ),
                # registrations / exits (ref: router.go:211-223)
                web.post("/eth/v1/validator/register_validator", self._register_validator),
                web.post("/eth/v1/beacon/pool/voluntary_exits", self._voluntary_exit),
                # duties (ref: router.go:97-113)
                web.post("/eth/v1/validator/duties/attester/{epoch}", self._attester_duties),
                web.get("/eth/v1/validator/duties/proposer/{epoch}", self._proposer_duties),
                web.post("/eth/v1/validator/duties/sync/{epoch}", self._sync_duties),
                # validators mapping (ref: validatorapi.go:1080)
                web.get(
                    "/eth/v1/beacon/states/{state_id}/validators", self._get_validators
                ),
                web.post(
                    "/eth/v1/beacon/states/{state_id}/validators", self._get_validators
                ),
                web.get(
                    "/eth/v1/beacon/states/{state_id}/validators/{validator_id}",
                    self._get_validator,
                ),
                # accepted no-ops the VC expects 200 from
                web.post("/eth/v1/validator/prepare_beacon_proposer", self._ok),
                web.post("/eth/v1/validator/beacon_committee_subscriptions", self._ok),
                web.post("/eth/v1/validator/sync_committee_subscriptions", self._ok),
                # head block root for sync-committee messages — blocks on
                # the cluster-agreed SYNC_MESSAGE root so every node's VC
                # signs the same root (the reference proxies this to the BN
                # and relies on BN agreement; consensus is this framework's
                # redesign for the same endpoint)
                web.get("/eth/v1/beacon/blocks/head/root", self._head_root),
                # node / chain metadata
                web.get("/eth/v1/node/version", self._node_version),
                web.get("/eth/v1/node/syncing", self._syncing),
                web.get("/eth/v1/node/health", self._health),
                web.get("/eth/v1/beacon/genesis", self._genesis),
                web.get("/eth/v1/config/spec", self._spec),
                web.get("/eth/v1/config/fork_schedule", self._fork_schedule),
                web.get("/eth/v1/beacon/states/{state_id}/fork", self._state_fork),
            ]
        )
        # everything else is proxied verbatim to the upstream beacon node
        # when one is configured (ref: router.go proxyHandler — the
        # reference forwards unmatched beacon-API traffic to the BN)
        self.app.router.add_route("*", "/{tail:.*}", self._proxy)
        self._runner: web.AppRunner | None = None
        self.proxy_url: str | None = None
        self._proxy_session = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        return site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._proxy_session is not None:
            await self._proxy_session.close()
            self._proxy_session = None
        if self._runner:
            await self._runner.cleanup()

    # hop-by-hop headers never forwarded in either direction (RFC 9110 §7.6)
    _HOP_HEADERS = frozenset(
        (
            "host",
            "connection",
            "content-length",
            "transfer-encoding",
            "keep-alive",
            "upgrade",
            "proxy-authenticate",
            "proxy-authorization",
            "te",
            "trailer",
        )
    )

    async def _proxy(self, request: web.Request) -> web.Response:
        if not self.proxy_url:
            return _err(404, f"unknown endpoint {request.path}")
        import aiohttp

        if self._proxy_session is None or self._proxy_session.closed:
            # one pooled session for the VC hot path — per-request
            # sessions would pay TCP/TLS setup on every proxied call
            self._proxy_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=10)
            )
        url = self.proxy_url.rstrip("/") + request.path_qs
        try:
            async with self._proxy_session.request(
                request.method,
                url,
                data=await request.read(),
                headers={
                    k: v
                    for k, v in request.headers.items()
                    if k.lower() not in self._HOP_HEADERS
                },
            ) as resp:
                body = await resp.read()
                # forward end-to-end response headers: the VC needs e.g.
                # Eth-Consensus-Version to decode fork-aware bodies.
                # content-encoding is dropped too: aiohttp has already
                # decompressed the body we are about to send verbatim
                headers = {
                    k: v
                    for k, v in resp.headers.items()
                    if k.lower() not in self._HOP_HEADERS
                    and k.lower() not in ("content-type", "content-encoding")
                }
                return web.Response(
                    status=resp.status,
                    body=body,
                    content_type=resp.content_type,
                    headers=headers,
                )
        except Exception as e:
            return _err(502, f"beacon proxy failed: {e}")

    # -- pubkey resolution -------------------------------------------------

    def _resolve_pubkey(self, pk_hex: str) -> PubKey:
        """Accept a group pubkey or this node's pubshare for it
        (the VC's keystores hold pubshares, ref: validatorapi.go:1167)."""
        pk_hex = pk_hex.lower()
        if pk_hex in self._group_by_pubshare:
            return self._group_by_pubshare[pk_hex]
        return PubKey(pk_hex)

    # -- attester ----------------------------------------------------------

    async def _attestation_data(self, request: web.Request) -> web.Response:
        """ref: router.go:115 attestation_data -> blocking DutyDB await."""
        try:
            slot = int(request.query["slot"])
            committee_index = int(request.query["committee_index"])
        except (KeyError, ValueError):
            return _err(400, "slot and committee_index required")
        try:
            data = await self.vapi.attestation_data(slot, committee_index)
        except VapiError as e:
            return _err(404, str(e))
        return web.json_response({"data": _att_data_json(data)})

    async def _submit_attestations(self, request: web.Request) -> web.Response:
        """ref: router.go:121 + validatorapi.go:274."""
        try:
            body = await request.json()
            if isinstance(body, dict):  # v2 shape {version, data}
                body = body["data"]
            atts = [_attestation_from_json(a) for a in body]
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed attestation: {e}")
        try:
            await self.vapi.submit_attestations(atts)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    # -- proposer ----------------------------------------------------------

    async def _produce_block_v3(self, request: web.Request) -> web.Response:
        """GET /eth/v3/validator/blocks/{slot}?randao_reveal=0x...

        The randao reveal IS this node's partial randao signature; it is
        verified + stored, the aggregated randao unblocks the proposal
        fetcher, and the response blocks until cluster consensus on the
        block (ref: validatorapi.go:335-399 Proposal)."""
        try:
            slot = int(request.match_info["slot"])
            randao = _unhex(request.query["randao_reveal"])
        except (KeyError, ValueError):
            return _err(400, "slot and randao_reveal required")
        defs = (
            self.vapi._duty_defs(Duty(slot, DutyType.PROPOSER))
            if self.vapi._duty_defs
            else {}
        )
        if not defs:
            return _err(404, f"no proposer duty at slot {slot}")
        # Key by PUBKEY, not an arbitrary duty entry: the randao reveal is
        # a partial signature by exactly one validator's share, so the
        # candidate whose pubshare verifies it identifies the proposer —
        # correct even when two cluster validators propose in the same
        # slot (ref: router.go maps proposals by pubkey).
        pubkey, last_err = None, None
        for candidate in defs:
            try:
                await self.vapi.submit_randao(slot, candidate, randao)
                pubkey = candidate
                break
            except VapiError as e:
                last_err = e
        if pubkey is None:
            return _err(400, f"randao reveal matches no proposer: {last_err}")
        try:
            proposal = await self.vapi.proposal(slot, pubkey)
        except VapiError as e:
            return _err(400, str(e))
        headers = {
            "Eth-Consensus-Version": proposal.version,
            "Eth-Execution-Payload-Blinded": str(proposal.blinded).lower(),
            "Eth-Execution-Payload-Value": "0",
            "Eth-Consensus-Block-Value": "0",
        }
        if "application/octet-stream" in request.headers.get("Accept", ""):
            # SSZ response (Lighthouse-style clients prefer it for blocks)
            return web.Response(
                body=proposal_data_ssz(proposal),
                content_type="application/octet-stream",
                headers=headers,
            )
        return web.json_response(
            {
                "version": proposal.version,
                "execution_payload_blinded": proposal.blinded,
                "execution_payload_value": "0",
                "consensus_block_value": "0",
                "data": proposal_data_json(proposal),
            },
            headers=headers,
        )

    async def _submit_block(self, request: web.Request) -> web.Response:
        """Accepts the spec publishBlock/publishBlindedBlock POST body:
        a SignedBeaconBlock {message, signature} (or deneb signed block
        contents {signed_block, kzg_proofs, blobs}), with the fork taken
        from the Eth-Consensus-Version header when present
        (ref: router.go:157-175 + validatorapi.go:490 SubmitProposal)."""
        blinded = "blinded_blocks" in request.path
        version = request.headers.get("Eth-Consensus-Version")
        try:
            # branch on the RAW header: aiohttp's content_type property
            # defaults to octet-stream when the header is absent, which
            # would misroute header-less JSON POSTs to the SSZ path
            if "octet-stream" in request.headers.get("Content-Type", ""):
                # SSZ body: the spec requires the consensus-version header
                if not version:
                    return _err(
                        400,
                        "Eth-Consensus-Version header required for SSZ",
                    )
                proposal, signature = signed_proposal_from_ssz(
                    await request.read(), blinded, version
                )
            else:
                j = await request.json()
                proposal, signature = signed_proposal_from_json(
                    j, blinded, version
                )
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed block: {e}")
        # key by PUBKEY via the block's proposer index (ref: router.go
        # submitProposal resolves the proposal by pubkey, never "the
        # first duty at this slot")
        pubkey = self._pubkey_by_index.get(proposal.proposer_index)
        if pubkey is None:
            # router built without a validators mapping: resolve through
            # the slot's proposer duty definitions instead
            defs = (
                self.vapi._duty_defs(Duty(proposal.slot, DutyType.PROPOSER))
                if self.vapi._duty_defs
                else {}
            )
            for pk, dd in defs.items():
                if getattr(dd, "validator_index", None) == proposal.proposer_index:
                    pubkey = pk
                    break
            # no single-def fallback: attributing a mismatched
            # proposer_index to the slot's only duty holder would be
            # caught by share-signature verification downstream, but
            # masks the VC's actual misconfiguration as a bad signature;
            # the 404 below is the actionable answer
        if pubkey is None:
            return _err(
                404,
                f"unknown proposer index {proposal.proposer_index} "
                f"at slot {proposal.slot}",
            )
        try:
            await self.vapi.submit_proposal(pubkey, proposal, signature)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    # -- aggregator --------------------------------------------------------

    async def _beacon_committee_selections(self, request: web.Request) -> web.Response:
        """Partial selection proofs in, threshold-aggregated proofs out
        (ref: validatorapi.go:724 AggregateBeaconCommitteeSelections)."""
        try:
            body = await request.json()
            parsed = [
                (
                    self._resolve_pubkey_by_index(int(s["validator_index"])),
                    int(s["slot"]),
                    _unhex(s["selection_proof"]),
                )
                for s in body
            ]
        except (
            json.JSONDecodeError, KeyError, ValueError, TypeError, VapiError
        ) as e:
            return _err(400, f"malformed selections: {e}")
        out = []
        try:
            for pubkey, slot, proof in parsed:
                await self.vapi.submit_selection_proof(slot, pubkey, proof)
            for pubkey, slot, _ in parsed:
                agg = await self.vapi.aggregate_selection(slot, pubkey)
                out.append(
                    {
                        "validator_index": str(self.validators.get(pubkey, 0)),
                        "slot": str(slot),
                        "selection_proof": _hex(agg.signature),
                    }
                )
        except VapiError as e:
            return _err(400, str(e))
        return web.json_response({"data": out})

    async def _aggregate_attestation(self, request: web.Request) -> web.Response:
        try:
            slot = int(request.query["slot"])
            root = _unhex(request.query["attestation_data_root"])
        except (KeyError, ValueError):
            return _err(400, "slot and attestation_data_root required")
        try:
            agg = await self.vapi.aggregate_attestation(slot, root)
        except VapiError as e:
            return _err(404, str(e))
        # DutyDB stores the consensus AggregateAndProof; the endpoint
        # serves the aggregate attestation inside it.
        att = agg.aggregate if hasattr(agg, "aggregate") else agg
        return web.json_response(
            {"version": "deneb", "data": _attestation_json(att)}
        )

    async def _aggregate_and_proofs(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            if isinstance(body, dict):
                body = body["data"]
            items = []
            for sap in body:
                m = sap["message"]
                agg = AggregateAndProof(
                    aggregator_index=int(m["aggregator_index"]),
                    aggregate=_attestation_from_json(m["aggregate"]),
                    selection_proof=_unhex(m["selection_proof"]),
                )
                items.append((agg, _unhex(sap["signature"])))
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed aggregate: {e}")
        try:
            for agg, sig in items:
                pubkey = self._resolve_pubkey_by_index(agg.aggregator_index)
                await self.vapi.submit_aggregate_and_proof(pubkey, agg, sig)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    # -- sync committee ----------------------------------------------------

    async def _submit_sync_messages(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            msgs = [
                SyncCommitteeMessage(
                    slot=int(m["slot"]),
                    beacon_block_root=_unhex(m["beacon_block_root"]),
                    validator_index=int(m["validator_index"]),
                    signature=_unhex(m["signature"]),
                )
                for m in body
            ]
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed sync message: {e}")
        try:
            for m in msgs:
                pubkey = self._resolve_pubkey_by_index(m.validator_index)
                await self.vapi.submit_sync_message(
                    m.slot, pubkey, m, m.signature
                )
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    async def _sync_committee_selections(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            parsed = [
                (
                    self._resolve_pubkey_by_index(int(s["validator_index"])),
                    int(s["slot"]),
                    int(s["subcommittee_index"]),
                    _unhex(s["selection_proof"]),
                )
                for s in body
            ]
        except (
            json.JSONDecodeError, KeyError, ValueError, TypeError, VapiError
        ) as e:
            return _err(400, f"malformed selections: {e}")
        out = []
        try:
            for pubkey, slot, subidx, proof in parsed:
                await self.vapi.submit_sync_selection(slot, subidx, pubkey, proof)
            for pubkey, slot, subidx, _ in parsed:
                agg = await self.vapi.sync_selection_aggregate(slot, pubkey)
                out.append(
                    {
                        "validator_index": str(self.validators.get(pubkey, 0)),
                        "slot": str(slot),
                        "subcommittee_index": str(subidx),
                        "selection_proof": _hex(agg.signature),
                    }
                )
        except VapiError as e:
            return _err(400, str(e))
        return web.json_response({"data": out})

    async def _sync_contribution(self, request: web.Request) -> web.Response:
        try:
            slot = int(request.query["slot"])
            subidx = int(request.query["subcommittee_index"])
            root = _unhex(request.query["beacon_block_root"])
        except (KeyError, ValueError):
            return _err(400, "slot, subcommittee_index, beacon_block_root required")
        try:
            contrib = await self.vapi.sync_contribution(slot, subidx, root)
        except VapiError as e:
            return _err(404, str(e))
        return web.json_response({"data": _contribution_json(contrib)})

    async def _contribution_and_proofs(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            items = []
            for scp in body:
                m = scp["message"]
                cap = ContributionAndProof(
                    aggregator_index=int(m["aggregator_index"]),
                    contribution=_contribution_from_json(m["contribution"]),
                    selection_proof=_unhex(m["selection_proof"]),
                )
                items.append((cap, _unhex(scp["signature"])))
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed contribution: {e}")
        try:
            for cap, sig in items:
                pubkey = self._resolve_pubkey_by_index(cap.aggregator_index)
                await self.vapi.submit_contribution_and_proof(pubkey, cap, sig)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    # -- registrations / exits ---------------------------------------------

    async def _register_validator(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            items = []
            for r in body:
                m = r["message"]
                reg = ValidatorRegistration(
                    fee_recipient=_unhex(m["fee_recipient"]),
                    gas_limit=int(m["gas_limit"]),
                    timestamp=int(m["timestamp"]),
                    pubkey=_unhex(m["pubkey"]),
                )
                items.append((reg, _unhex(r["signature"])))
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed registration: {e}")
        try:
            for reg, sig in items:
                pubkey = self._resolve_pubkey("0x" + reg.pubkey.hex())
                await self.vapi.submit_registration(pubkey, reg, sig)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    async def _voluntary_exit(self, request: web.Request) -> web.Response:
        try:
            j = await request.json()
            exit_msg = VoluntaryExit(
                epoch=int(j["message"]["epoch"]),
                validator_index=int(j["message"]["validator_index"]),
            )
            signature = _unhex(j["signature"])
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            return _err(400, f"malformed exit: {e}")
        try:
            pubkey = self._resolve_pubkey_by_index(exit_msg.validator_index)
            await self.vapi.submit_exit(pubkey, exit_msg, signature)
        except VapiError as e:
            return _err(400, str(e))
        return web.Response(status=200)

    # -- duties ------------------------------------------------------------

    def _resolve_pubkey_by_index(self, vidx: int) -> PubKey:
        pk = self._pubkey_by_index.get(vidx)
        if pk is None:
            raise VapiError(f"unknown validator index {vidx}")
        return pk

    async def _attester_duties(self, request: web.Request) -> web.Response:
        if self.beacon is None:
            return _err(404, "no beacon client")
        epoch = int(request.match_info["epoch"])
        try:
            want = {int(i) for i in await request.json()}
        except (json.JSONDecodeError, ValueError, TypeError):
            want = set(self.validators.values())
        duties = await self.beacon.attester_duties(epoch, self.validators)
        out = [
            {
                "pubkey": d["pubkey"],
                "validator_index": str(d["validator_index"]),
                "committee_index": str(d["committee_index"]),
                "committee_length": str(d["committee_length"]),
                "committees_at_slot": str(d["committees_at_slot"]),
                "validator_committee_index": str(d["validator_committee_index"]),
                "slot": str(d["slot"]),
            }
            for d in duties
            if d["validator_index"] in want
        ]
        return web.json_response(
            {"dependent_root": _hex(bytes(32)), "data": out}
        )

    async def _proposer_duties(self, request: web.Request) -> web.Response:
        if self.beacon is None:
            return _err(404, "no beacon client")
        epoch = int(request.match_info["epoch"])
        duties = await self.beacon.proposer_duties(epoch, self.validators)
        out = [
            {
                "pubkey": d["pubkey"],
                "validator_index": str(d["validator_index"]),
                "slot": str(d["slot"]),
            }
            for d in duties
        ]
        return web.json_response(
            {"dependent_root": _hex(bytes(32)), "data": out}
        )

    async def _sync_duties(self, request: web.Request) -> web.Response:
        if self.beacon is None:
            return _err(404, "no beacon client")
        epoch = int(request.match_info["epoch"])
        try:
            want = {int(i) for i in await request.json()}
        except (json.JSONDecodeError, ValueError, TypeError):
            want = set(self.validators.values())
        duties = await self.beacon.sync_duties(epoch, self.validators)
        # serve the validator's REAL committee position — the scheduler
        # derives subcommittee (pos // 128) and in-subcommittee bit
        # (pos % 128) from the same position. Served positions are
        # limited to the FIRST (the one the scheduler drives) so the
        # VC's contribution queries always match a stored duty; extra
        # seats are a logged, documented limitation (scheduler.py).
        out = [
            {
                "pubkey": d["pubkey"],
                "validator_index": str(d["validator_index"]),
                "validator_sync_committee_indices": [
                    str(int(p))
                    for p in d.get(
                        "sync_committee_indices",
                        [d.get("subcommittee_index", 0) * 128],
                    )[:1]
                ],
            }
            for d in duties
            if d["validator_index"] in want
        ]
        return web.json_response({"data": out})

    # -- validators mapping ------------------------------------------------

    def _validator_json(self, pubkey_hex: str, vidx: int) -> dict:
        return {
            "index": str(vidx),
            "balance": "32000000000",
            "status": "active_ongoing",
            "validator": {
                "pubkey": pubkey_hex,
                "withdrawal_credentials": _hex(bytes(32)),
                "effective_balance": "32000000000",
                "slashed": False,
                "activation_eligibility_epoch": "0",
                "activation_epoch": "0",
                "exit_epoch": "18446744073709551615",
                "withdrawable_epoch": "18446744073709551615",
            },
        }

    async def _get_validators(self, request: web.Request) -> web.Response:
        """Serves cluster validators; querying by this node's pubshare
        returns the entry with the pubshare as pubkey so an unmodified VC
        sees "its" keys as active (ref: validatorapi.go:1080,1167)."""
        ids: list[str] = []
        if request.method == "POST":
            try:
                j = await request.json()
                ids = list(j.get("ids", []))
            except (json.JSONDecodeError, AttributeError):
                ids = []
        else:
            # beacon API sends repeated ?id=...&id=... keys; comma-separated
            # values inside each are also accepted
            ids = [
                part
                for raw in request.query.getall("id", [])
                for part in raw.split(",")
                if part
            ]
        out = []
        if not ids:
            for pk, vidx in sorted(self.validators.items()):
                out.append(self._validator_json(pk, vidx))
        else:
            for ident in ids:
                ident = ident.lower()
                group = self._resolve_pubkey(ident) if ident.startswith("0x") else None
                if group is not None and group in self.validators:
                    out.append(
                        self._validator_json(ident, self.validators[group])
                    )
                elif ident.isdigit():
                    try:
                        pk = self._resolve_pubkey_by_index(int(ident))
                        out.append(self._validator_json(pk, int(ident)))
                    except VapiError:
                        pass
        return web.json_response({"data": out})

    async def _get_validator(self, request: web.Request) -> web.Response:
        ident = request.match_info["validator_id"].lower()
        if ident.startswith("0x"):
            group = self._resolve_pubkey(ident)
            if group in self.validators:
                return web.json_response(
                    {"data": self._validator_json(ident, self.validators[group])}
                )
        elif ident.isdigit():
            try:
                pk = self._resolve_pubkey_by_index(int(ident))
                return web.json_response(
                    {"data": self._validator_json(pk, int(ident))}
                )
            except VapiError:
                pass
        return _err(404, f"validator {ident} not found")

    async def _head_root(self, request: web.Request) -> web.Response:
        """Cluster-agreed head root for sync-committee signing. `slot` may
        be passed to select the SYNC_MESSAGE duty (defaults to the current
        slot by genesis arithmetic)."""
        try:
            if "slot" in request.query:
                slot = int(request.query["slot"])
            else:
                import time as _t

                # wall by design: "current slot" is wall-clock genesis
                # arithmetic, same timeline the VC's BN view uses
                slot = self.clock.slot_at(_t.time())  # lint: allow(monotonic-clock)
        except ValueError:
            return _err(400, "bad slot")
        defs = (
            self.vapi._duty_defs(Duty(slot, DutyType.SYNC_MESSAGE))
            if self.vapi._duty_defs
            else {}
        )
        if not defs:
            return _err(404, f"no sync duty at slot {slot}")
        duty = await self.vapi.sync_message_duty(slot, next(iter(defs)))
        return web.json_response(
            {"data": {"root": _hex(duty.beacon_block_root)}}
        )

    # -- metadata ----------------------------------------------------------

    async def _ok(self, request: web.Request) -> web.Response:
        return web.Response(status=200)

    async def _node_version(self, request: web.Request) -> web.Response:
        from charon_tpu import __version__ as version

        return web.json_response({"data": {"version": f"charon-tpu/{version}"}})

    async def _syncing(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "data": {
                    "head_slot": "0",
                    "sync_distance": "0",
                    "is_syncing": False,
                    "is_optimistic": False,
                }
            }
        )

    async def _health(self, request: web.Request) -> web.Response:
        return web.Response(status=200)

    async def _genesis(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "data": {
                    "genesis_time": str(int(self.genesis_time)),
                    "genesis_validators_root": _hex(
                        self.vapi.fork.genesis_validators_root
                    ),
                    "genesis_fork_version": _hex(
                        self.vapi.fork.genesis_fork_version
                    ),
                }
            }
        )

    async def _spec(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "data": {
                    "SECONDS_PER_SLOT": str(int(self.slot_duration) or 1),
                    "SLOTS_PER_EPOCH": str(self.slots_per_epoch),
                    "DOMAIN_BEACON_ATTESTER": "0x01000000",
                    "DOMAIN_BEACON_PROPOSER": "0x00000000",
                    "DOMAIN_RANDAO": "0x02000000",
                }
            }
        )

    async def _fork_schedule(self, request: web.Request) -> web.Response:
        fv = _hex(self.vapi.fork.fork_version)
        return web.json_response(
            {
                "data": [
                    {
                        "previous_version": fv,
                        "current_version": fv,
                        "epoch": "0",
                    }
                ]
            }
        )

    async def _state_fork(self, request: web.Request) -> web.Response:
        fv = _hex(self.vapi.fork.fork_version)
        return web.json_response(
            {
                "data": {
                    "previous_version": fv,
                    "current_version": fv,
                    "epoch": "0",
                },
                "execution_optimistic": False,
            }
        )
