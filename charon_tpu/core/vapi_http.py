"""HTTP router for the ValidatorAPI: the eth2 beacon API served to VCs.

Mirrors ref: core/validatorapi/router.go:97-253 — the intercepted endpoint
set (attestation data, attestation submission, proposals, randao via the
proposal flow, duties, node endpoints) served locally with blocking
awaits; everything else would proxy to the upstream beacon node
(proxy handler router.go; here: 501 with a clear error until the proxy
lands).

JSON schema follows the eth2 beacon API shapes for the implemented
endpoints (integers as strings, 0x-hex byte fields).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from aiohttp import web

from charon_tpu.core.eth2data import (
    Attestation,
    AttestationData,
    Checkpoint,
    Proposal,
)
from charon_tpu.core.types import PubKey
from charon_tpu.core.validatorapi import ValidatorAPI, VapiError


def _att_data_json(d: AttestationData) -> dict:
    return {
        "slot": str(d.slot),
        "index": str(d.index),
        "beacon_block_root": "0x" + d.beacon_block_root.hex(),
        "source": {
            "epoch": str(d.source.epoch),
            "root": "0x" + d.source.root.hex(),
        },
        "target": {
            "epoch": str(d.target.epoch),
            "root": "0x" + d.target.root.hex(),
        },
    }


def _att_data_from_json(j: dict) -> AttestationData:
    return AttestationData(
        slot=int(j["slot"]),
        index=int(j["index"]),
        beacon_block_root=bytes.fromhex(j["beacon_block_root"][2:]),
        source=Checkpoint(
            int(j["source"]["epoch"]), bytes.fromhex(j["source"]["root"][2:])
        ),
        target=Checkpoint(
            int(j["target"]["epoch"]), bytes.fromhex(j["target"]["root"][2:])
        ),
    )


def _bits_from_hex(hexstr: str) -> tuple[bool, ...]:
    """Eth2 SSZ bitlist hex -> bool tuple (delimiter bit trimmed)."""
    raw = bytes.fromhex(hexstr[2:])
    bits = []
    for byte in raw:
        for i in range(8):
            bits.append(bool(byte >> i & 1))
    # strip from the last set bit (the length delimiter)
    while bits and not bits[-1]:
        bits.pop()
    if bits:
        bits.pop()  # remove delimiter
    return tuple(bits)


def _bits_to_hex(bits: tuple[bool, ...]) -> str:
    all_bits = list(bits) + [True]  # delimiter
    data = bytearray((len(all_bits) + 7) // 8)
    for i, b in enumerate(all_bits):
        if b:
            data[i // 8] |= 1 << (i % 8)
    return "0x" + bytes(data).hex()


class VapiRouter:
    def __init__(self, vapi: ValidatorAPI) -> None:
        self.vapi = vapi
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get(
                    "/eth/v1/validator/attestation_data", self._attestation_data
                ),
                web.post(
                    "/eth/v1/beacon/pool/attestations", self._submit_attestations
                ),
                web.get("/eth/v1/node/version", self._node_version),
                web.get("/eth/v1/node/syncing", self._syncing),
            ]
        )
        self._runner: web.AppRunner | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        return site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- handlers ---------------------------------------------------------

    async def _attestation_data(self, request: web.Request) -> web.Response:
        """ref: router.go:115 attestation_data -> blocking DutyDB await."""
        try:
            slot = int(request.query["slot"])
            committee_index = int(request.query["committee_index"])
        except (KeyError, ValueError):
            return web.json_response(
                {"code": 400, "message": "slot and committee_index required"},
                status=400,
            )
        try:
            data = await self.vapi.attestation_data(slot, committee_index)
        except VapiError as e:
            return web.json_response({"code": 404, "message": str(e)}, status=404)
        return web.json_response({"data": _att_data_json(data)})

    async def _submit_attestations(self, request: web.Request) -> web.Response:
        """ref: router.go:121 + validatorapi.go:274."""
        try:
            body = await request.json()
            atts = [
                Attestation(
                    aggregation_bits=_bits_from_hex(a["aggregation_bits"]),
                    data=_att_data_from_json(a["data"]),
                    signature=bytes.fromhex(a["signature"][2:]),
                )
                for a in body
            ]
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            return web.json_response(
                {"code": 400, "message": f"malformed attestation: {e}"},
                status=400,
            )
        try:
            await self.vapi.submit_attestations(atts)
        except VapiError as e:
            return web.json_response({"code": 400, "message": str(e)}, status=400)
        return web.Response(status=200)

    async def _node_version(self, request: web.Request) -> web.Response:
        from charon_tpu import __version__ as version

        return web.json_response(
            {"data": {"version": f"charon-tpu/{version}"}}
        )

    async def _syncing(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "data": {
                    "head_slot": "0",
                    "sync_distance": "0",
                    "is_syncing": False,
                    "is_optimistic": False,
                }
            }
        )
