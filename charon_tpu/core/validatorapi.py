"""ValidatorAPI: the beacon-node facade served to the downstream VC.

Mirrors ref: core/validatorapi/validatorapi.go — maps group pubkeys to this
node's pubshares (validatorapi.go:1080,1167), serves duty data with
blocking awaits against DutyDB, verifies every incoming partial signature
against the node's pubshare (validatorapi.go:1213) and pushes it into
ParSigDB as a ParSignedData.

This module is the transport-agnostic component; the HTTP router
(charon_tpu/core/vapi_http.py) exposes it as the eth2 beacon API the same
way ref core/validatorapi/router.go does. Partial-signature verification
is batched: one device call per submission set.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Awaitable, Callable, Sequence

from charon_tpu import tbls
from charon_tpu.core.eth2data import (
    Attestation,
    AttestationDuty,
    ParSignedData,
    Proposal,
    SignedData,
)
from charon_tpu.core.types import Duty, DutyType, PubKey, pubkey_to_bytes
from charon_tpu.eth2util.signing import ForkInfo


class VapiError(Exception):
    pass


@dataclass
class ValidatorAPI:
    """share_idx: this node's 1-based share index; pubshares maps group
    pubkey -> this node's compressed pubshare bytes."""

    share_idx: int
    pubshares: dict[PubKey, bytes]
    fork: ForkInfo
    slots_per_epoch: int = 32
    # optional core.cryptoplane.SlotCoalescer: partial-sig pubshare checks
    # from concurrent VC submissions merge into one sharded device program
    plane: object | None = None

    def __post_init__(self) -> None:
        self._subs: list = []
        self._await_attestation = None
        self._await_proposal = None
        self._await_agg_att = None
        self._await_contrib = None
        self._await_sync_msg = None
        self._pubkey_by_att = None
        self._duty_defs = None
        self._await_aggregated = None

    # -- wiring ------------------------------------------------------------

    def subscribe(self, sub) -> None:
        self._subs.append(sub)

    def register_await_attestation(self, fn) -> None:
        self._await_attestation = fn

    def register_await_proposal(self, fn) -> None:
        self._await_proposal = fn

    def register_await_aggregated_attestation(self, fn) -> None:
        self._await_agg_att = fn

    def register_await_sync_contribution(self, fn) -> None:
        self._await_contrib = fn

    def register_await_sync_message(self, fn) -> None:
        self._await_sync_msg = fn

    def register_pubkey_by_attestation(self, fn) -> None:
        self._pubkey_by_att = fn

    def register_get_duty_definition(self, fn) -> None:
        self._duty_defs = fn

    def register_await_aggregated(self, fn) -> None:
        """AggSigDB await — serves aggregated selection proofs back to the
        VC (ref: validatorapi.go:724 AggregateBeaconCommitteeSelections
        returns combined selections, not partials)."""
        self._await_aggregated = fn

    # -- queries (VC pulls duty data; blocking until consensus) ------------

    async def attestation_data(self, slot: int, committee_index: int):
        """GET /eth/v1/validator/attestation_data analogue
        (ref: validatorapi.go:261 via dutydb.AwaitAttestation)."""
        duty = Duty(slot, DutyType.ATTESTER)
        defs = self._duty_defs(duty) if self._duty_defs else {}
        for pubkey, d in defs.items():
            if d.committee_index == committee_index:
                att_duty = await self._await_attestation(slot, pubkey)
                return att_duty.data
        raise VapiError(f"no attester duty for slot {slot} committee {committee_index}")

    async def proposal(self, slot: int, pubkey: PubKey) -> Proposal:
        return await self._await_proposal(slot, pubkey)

    # -- submissions (VC pushes partial signatures) ------------------------

    async def submit_attestations(self, atts: Sequence[Attestation]) -> None:
        """POST /eth/v1/beacon/pool/attestations analogue
        (ref: validatorapi.go:274 SubmitAttestations)."""
        by_duty: dict[Duty, dict[PubKey, ParSignedData]] = {}
        items = []
        metas = []
        for att in atts:
            slot = att.data.slot
            root = att.data.hash_tree_root()
            pubkey = self._pubkey_by_att(slot, root)
            if pubkey is None:
                raise VapiError("unknown attestation (no DutyDB entry)")
            signed = SignedData("attestation", att, att.signature)
            items.append(self._verify_item(pubkey, signed, slot))
            metas.append((Duty(slot, DutyType.ATTESTER), pubkey, signed))
        await self._check_batch(items)
        for duty, pubkey, signed in metas:
            by_duty.setdefault(duty, {})[pubkey] = ParSignedData(
                data=signed, share_idx=self.share_idx
            )
        for duty, signed_set in by_duty.items():
            for sub in self._subs:
                await sub(duty, signed_set)

    async def submit_proposal(self, pubkey: PubKey, proposal: Proposal, signature: bytes) -> None:
        signed = SignedData("block", proposal, signature)
        await self._check_batch([self._verify_item(pubkey, signed, proposal.slot)])
        duty = Duty(proposal.slot, DutyType.PROPOSER)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def submit_randao(self, slot: int, pubkey: PubKey, signature: bytes) -> None:
        """Randao reveals arrive with proposal requests
        (ref: validatorapi.go:335 Proposal flow)."""
        epoch = slot // self.slots_per_epoch
        signed = SignedData("randao", epoch, signature)
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.RANDAO)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def submit_selection_proof(self, slot: int, pubkey: PubKey, signature: bytes) -> None:
        """Beacon-committee selection partials
        (ref: validatorapi.go:724 AggregateBeaconCommitteeSelections)."""
        signed = SignedData("selection_proof", slot, signature)
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.PREPARE_AGGREGATOR)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def aggregate_attestation(self, slot: int, att_data_root: bytes):
        """Blocking fetch of the cluster-agreed aggregate."""
        return await self._await_agg_att(slot, att_data_root)

    async def submit_aggregate_and_proof(self, pubkey: PubKey, agg, signature: bytes) -> None:
        signed = SignedData("aggregate_and_proof", agg, signature)
        await self._check_batch(
            [self._verify_item(pubkey, signed, agg.aggregate.data.slot)]
        )
        duty = Duty(agg.aggregate.data.slot, DutyType.AGGREGATOR)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def aggregate_selection(self, slot: int, pubkey: PubKey):
        """Blocking fetch of the threshold-aggregated beacon-committee
        selection proof (ref: validatorapi.go:724 returns the combined
        proof after cluster-wide aggregation)."""
        duty = Duty(slot, DutyType.PREPARE_AGGREGATOR)
        return await self._await_aggregated(duty, pubkey)

    async def submit_sync_selection(
        self, slot: int, subcommittee_index: int, pubkey: PubKey, signature: bytes
    ) -> None:
        """Sync-committee selection partials
        (ref: validatorapi.go AggregateSyncCommitteeSelections)."""
        from charon_tpu.core.eth2data import SyncSelectionData

        payload = SyncSelectionData(slot, subcommittee_index)
        signed = SignedData("sync_selection", payload, signature)
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.PREPARE_SYNC_CONTRIBUTION)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def sync_selection_aggregate(self, slot: int, pubkey: PubKey):
        duty = Duty(slot, DutyType.PREPARE_SYNC_CONTRIBUTION)
        return await self._await_aggregated(duty, pubkey)

    async def sync_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: bytes
    ):
        """Blocking fetch of the cluster-agreed sync contribution."""
        return await self._await_contrib(
            slot, subcommittee_index, beacon_block_root
        )

    async def submit_contribution_and_proof(
        self, pubkey: PubKey, cap, signature: bytes
    ) -> None:
        signed = SignedData("contribution_and_proof", cap, signature)
        slot = cap.contribution.slot
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.SYNC_CONTRIBUTION)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def sync_message_duty(self, slot: int, pubkey: PubKey):
        return await self._await_sync_msg(slot, pubkey)

    async def submit_sync_message(self, slot: int, pubkey: PubKey, msg, signature: bytes) -> None:
        signed = SignedData("sync_message", msg, signature)
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.SYNC_MESSAGE)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def submit_exit(self, pubkey: PubKey, exit_msg, signature: bytes) -> None:
        """Voluntary exit partial (ref: exit flow, validatorapi exit
        endpoints + cmd/exit_sign.go)."""
        signed = SignedData("exit", exit_msg, signature)
        slot = exit_msg.epoch * self.slots_per_epoch
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.EXIT)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    async def submit_registration(self, pubkey: PubKey, reg, signature: bytes, slot: int = 0) -> None:
        signed = SignedData("registration", reg, signature)
        await self._check_batch([self._verify_item(pubkey, signed, slot)])
        duty = Duty(slot, DutyType.BUILDER_REGISTRATION)
        for sub in self._subs:
            await sub(duty, {pubkey: ParSignedData(signed, self.share_idx)})

    # -- helpers -----------------------------------------------------------

    def _verify_item(self, pubkey: PubKey, signed: SignedData, slot: int):
        pubshare = self.pubshares.get(pubkey)
        if pubshare is None:
            raise VapiError(f"unknown validator {pubkey}")
        root = signed.signing_root(self.fork, slot // self.slots_per_epoch)
        return (pubshare, root, signed.signature)

    async def _check_batch(self, items) -> None:
        """Verify partial signatures against pubshares — batched
        (ref: validatorapi.go:1213 one herumi call per signature). With a
        crypto plane installed, concurrent submissions coalesce into one
        sharded device program."""
        if self.plane is not None:
            import asyncio

            from charon_tpu.core.cryptosvc import PlaneOverloadError

            try:
                ok = await self.plane.verify(items)
            except PlaneOverloadError:
                # admission shed (core/cryptosvc backpressure): this
                # VC's submission verifies on the host tbls rung, off
                # the event loop (host BLS would stall it for seconds)
                ok = await asyncio.get_running_loop().run_in_executor(
                    None, tbls.verify_batch, items
                )
        else:
            # plane-less rung (simnet/unit wiring + the no-accelerator
            # floor): deliberately INLINE — an executor hop here GIL-
            # convoys the busy loop and reorders duty timing (measured
            # 7-17x e2e slowdown); production wires the plane, whose
            # path above is truly async
            ok = tbls.verify_batch(items)  # lint: allow(event-loop-blocking)
        if not all(ok):
            raise VapiError("partial signature failed pubshare verification")
