"""QBFT consensus adapter: binds the pure engine to the duty workflow.

Mirrors ref: core/consensus/qbft — consensus runs over 32-byte value
hashes with the actual unsigned-data sets carried alongside in a
values-by-hash cache (ref: core/consensus/qbft/transport.go:63-90), a
deterministic round-robin leader (ref qbft.go:706), and per-duty engine
instances started by propose/participate (ref qbft.go:247,317).

The in-memory transport is the simnet path; the p2p transport (signed
protobuf messages) plugs into the same MsgNet interface.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Awaitable, Callable

from charon_tpu.core import qbft
from charon_tpu.core.types import Duty, PubKey

DecidedSub = Callable[[Duty, dict[PubKey, object]], Awaitable[None]]


def value_hash(unsigned_set: dict[PubKey, object]) -> bytes:
    """Canonical hash of an unsigned duty data set: consensus agrees on
    hashes, values travel out-of-band (ref: transport.go:63 values-by-hash).
    Frozen dataclasses repr deterministically."""
    items = sorted(unsigned_set.items())
    return hashlib.sha256(repr(items).encode()).digest()


class MemMsgNet:
    """In-memory QBFT message fabric for one cluster: routes engine
    messages and replicates the value cache (simnet only — production uses
    the signed p2p transport)."""

    def __init__(self) -> None:
        self.nodes: list["QBFTConsensus"] = []

    def attach(self, node: "QBFTConsensus") -> int:
        self.nodes.append(node)
        return len(self.nodes) - 1

    async def broadcast(self, from_idx: int, duty: Duty, msg: qbft.Msg, values) -> None:
        for node in self.nodes:
            if node.node_idx != from_idx:
                node.deliver(duty, msg, values)


class QBFTConsensus:
    protocol_id = "qbft/2.0.0"

    def __init__(
        self,
        net: MemMsgNet,
        nodes: int,
        round_timeout: float = 0.75,
        round_increase: float = 0.25,
    ) -> None:
        self.net = net
        self.node_idx = net.attach(self)

        def leader(instance, rnd: int) -> int:
            """Deterministic round-robin (ref: qbft.go:706)."""
            h = int.from_bytes(
                hashlib.sha256(repr(instance).encode()).digest()[:8], "big"
            )
            return (h + rnd) % nodes

        self.defn = qbft.Definition(
            nodes=nodes,
            leader=leader,
            # ref-equivalent increasing round timer
            # (core/consensus/utils/roundtimer.go:17-19)
            timeout=lambda r: round_timeout + round_increase * r,
        )
        self._subs: list[DecidedSub] = []
        self._values: dict[bytes, dict[PubKey, object]] = {}
        self._instances: dict[Duty, qbft.Transport] = {}
        self._running: dict[Duty, asyncio.Task] = {}
        self._decided: set[Duty] = set()

    def subscribe(self, sub: DecidedSub) -> None:
        self._subs.append(sub)

    # -- engine plumbing ---------------------------------------------------

    def _transport(self, duty: Duty) -> qbft.Transport:
        tr = self._instances.get(duty)
        if tr is None:

            async def bcast(msg: qbft.Msg) -> None:
                await self.net.broadcast(
                    self.node_idx, duty, msg, dict(self._values)
                )

            tr = qbft.Transport(bcast)
            self._instances[duty] = tr
        return tr

    def deliver(self, duty: Duty, msg: qbft.Msg, values) -> None:
        """Incoming message from the fabric; values-by-hash cache merge."""
        self._values.update(values)
        self._transport(duty).inbox.put_nowait(msg)

    def _ensure_running(self, duty: Duty, value_hash_or_none) -> asyncio.Task:
        task = self._running.get(duty)
        if task is None:
            tr = self._transport(duty)
            task = asyncio.create_task(
                self._run_instance(duty, tr, value_hash_or_none)
            )
            self._running[duty] = task
        return task

    async def _run_instance(self, duty: Duty, tr: qbft.Transport, vhash) -> None:
        decided_hash = await qbft.run(
            self.defn, tr, duty, self.node_idx, vhash
        )
        if duty in self._decided:
            return
        self._decided.add(duty)
        unsigned_set = self._values.get(decided_hash)
        if unsigned_set is None:
            raise RuntimeError(
                f"decided hash with no value in cache for {duty}"
            )
        for sub in self._subs:
            await sub(duty, unsigned_set)

    # -- workflow API ------------------------------------------------------

    async def propose(self, duty: Duty, unsigned_set: dict[PubKey, object]) -> None:
        """ref: core/consensus/qbft/qbft.go:247 Propose."""
        vhash = value_hash(unsigned_set)
        self._values[vhash] = unsigned_set
        task = self._ensure_running(duty, vhash)
        await asyncio.shield(task)

    async def participate(self, duty: Duty) -> None:
        """Join the instance without a proposal
        (ref: core/consensus/qbft/qbft.go:317 Participate)."""
        self._ensure_running(duty, None)
