"""QBFT consensus adapter: binds the pure engine to the duty workflow.

Mirrors ref: core/consensus/qbft — consensus runs over 32-byte value
hashes with the actual unsigned-data sets carried alongside in a
values-by-hash cache (ref: core/consensus/qbft/transport.go:63-90), a
deterministic round-robin leader (ref qbft.go:706), and per-duty engine
instances started by propose/participate (ref qbft.go:247,317).

The in-memory transport is the simnet path; the p2p transport (signed
protobuf messages) plugs into the same MsgNet interface.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Awaitable, Callable

from charon_tpu.core import qbft
from charon_tpu.core.types import Duty, PubKey

DecidedSub = Callable[[Duty, dict[PubKey, object]], Awaitable[None]]


def value_hash(unsigned_set: dict[PubKey, object]) -> bytes:
    """Canonical hash of an unsigned duty data set: consensus agrees on
    hashes, values travel out-of-band (ref: transport.go:63 values-by-hash).
    Frozen dataclasses repr deterministically."""
    items = sorted(unsigned_set.items())
    return hashlib.sha256(repr(items).encode()).digest()


class MemMsgNet:
    """In-memory QBFT message fabric for one cluster: routes engine
    messages and replicates the value cache (simnet only — production uses
    the signed p2p transport)."""

    def __init__(self) -> None:
        self.nodes: list["QBFTConsensus"] = []

    def attach(self, node: "QBFTConsensus") -> int:
        self.nodes.append(node)
        return len(self.nodes) - 1

    async def broadcast(
        self,
        from_idx: int,
        duty: Duty,
        msg: qbft.Msg,
        values,
        tctx: str | None = None,
    ) -> None:
        # simulated network boundary: see parsigex.MemTransport.send
        from charon_tpu.app.tracer import detached

        for node in self.nodes:
            if node.node_idx != from_idx:
                with detached():
                    node.deliver(duty, msg, values, tctx=tctx, sender=from_idx)


class QBFTConsensus:
    protocol_id = "qbft/2.0.0"

    def __init__(
        self,
        net: MemMsgNet,
        nodes: int,
        round_timeout: float = 0.75,
        round_increase: float = 0.25,
        privkey=None,
        pubkeys: list[bytes] | None = None,
        gater=None,
        timer: str | None = None,
        linear_round_inc: float = qbft.LINEAR_ROUND_INC,
        tracer=None,  # app/tracer.Tracer; None = process-global
        evidence=None,  # core/evidence.EvidenceRegistry; None = unrecorded
    ) -> None:
        """`privkey`/`pubkeys` enable per-message k1 authentication
        (ref: core/consensus/qbft/transport.go:25-50 signs every msg,
        qbft.go:561 verifies each incl. piggybacked justifications). When
        provided, every outbound message is signed over qbft.msg_digest and
        every inbound message — and each of its justification messages — is
        verified against the per-index cluster pubkeys before the engine
        counts it.

        `timer` selects the round-timer strategy: "inc" (increasing,
        configured by round_timeout/round_increase) or "eager_dlinear"
        (double-eager-linear, configured by linear_round_inc). None picks
        per the EAGER_DOUBLE_LINEAR feature flag, mirroring
        ref: core/consensus/utils/roundtimer.go:26-37 GetTimerFunc +
        app/featureset/featureset.go:53 (stable → dlinear is the
        cluster default)."""
        self.net = net
        self.node_idx = net.attach(self)
        self.tracer = tracer
        self._privkey = privkey
        self._pubkeys = pubkeys
        # Byzantine-evidence ledger (core/evidence.EvidenceRegistry):
        # engine detections land here attributed by SHARE index (the
        # cluster-wide peer convention: share = engine node idx + 1).
        self.evidence = evidence
        # Duty gater: without it, deliver() would create transports and
        # value caches for ANY duty a byzantine-but-authenticated peer
        # names — unbounded memory (ref: consensus also gates inbound
        # duties, core/consensus/qbft/qbft.go handle()).
        self._gater = gater

        def leader(instance, rnd: int) -> int:
            """Deterministic round-robin (ref: qbft.go:706)."""
            h = int.from_bytes(
                hashlib.sha256(repr(instance).encode()).digest()[:8], "big"
            )
            return (h + rnd) % nodes

        def sign_msg(m: qbft.Msg) -> qbft.Msg:
            if privkey is None:
                return m
            from dataclasses import replace

            from charon_tpu.app import k1util

            return replace(
                m, signature=k1util.sign(privkey, qbft.msg_digest(m))
            )

        def is_valid(m: qbft.Msg) -> bool:
            if pubkeys is None:
                return True
            return self._verify_msg(m, check_justification=True)

        def verify_sender(m: qbft.Msg) -> bool:
            # outer signature only — the engine uses this to attribute
            # evidence (forged justifications, floods) to the sender
            if pubkeys is None:
                return True
            return self._verify_msg(m, check_justification=False)

        def on_evidence(source: int, kind: str) -> None:
            if self.evidence is not None:
                self.evidence.record(source + 1, kind)

        if timer is None:
            from charon_tpu.app import featureset

            timer = (
                "eager_dlinear"
                if featureset.enabled(featureset.Feature.EAGER_DOUBLE_LINEAR)
                else "inc"
            )
        if timer == "eager_dlinear":
            new_timer = lambda: qbft.DoubleEagerLinearRoundTimer(  # noqa: E731
                linear_round_inc
            )
        elif timer == "inc":
            new_timer = lambda: qbft.IncreasingRoundTimer(  # noqa: E731
                round_timeout, round_increase
            )
        else:
            raise ValueError(f"unknown round timer strategy: {timer}")
        self.timer_type = timer

        self.defn = qbft.Definition(
            nodes=nodes,
            leader=leader,
            # per-instance round timer, strategy selected above
            # (ref: core/consensus/utils/roundtimer.go:26-37)
            new_timer=new_timer,
            is_valid=is_valid,
            sign_msg=sign_msg,
            verify_sender=verify_sender,
            on_evidence=on_evidence,
        )
        self._subs: list[DecidedSub] = []
        # Consensus sniffer: bounded ring of recent message summaries
        # (in/out), served at /debug/consensus for post-mortem debugging
        # (ref: core/consensus/qbft/sniffer.go buffers instances for the
        # debugger endpoint, docs/consensus.md:74).
        from collections import deque

        self._sniffer: deque = deque(maxlen=512)
        # Per-duty values-by-hash cache: messages for one instance carry
        # only that instance's candidate values (ref: transport.go:63-90
        # keeps values per consensus instance, not globally).
        self._values: dict[Duty, dict[bytes, dict[PubKey, object]]] = {}
        self._instances: dict[Duty, qbft.Transport] = {}
        self._running: dict[Duty, asyncio.Task] = {}
        self._decided: set[Duty] = set()
        # most recent decide's {duty, round, duration, timer} + optional
        # observer (run.py wires it into the metrics catalogue)
        self.last_decided: dict | None = None
        self.on_decided_stats = None
        # flight-recorder edge (ISSUE 19): fired from the sniffer for
        # every ROUND_CHANGE observed in either direction —
        # on_round_change(duty, round, source, direction)
        self.on_round_change = None

    def subscribe(self, sub: DecidedSub) -> None:
        self._subs.append(sub)

    def _verify_msg(self, m: qbft.Msg, check_justification: bool) -> bool:
        """Signature check against the sender's cluster pubkey; recurses
        into justification messages so a byzantine leader cannot fabricate
        quorums of piggybacked ROUND-CHANGE/PREPARE messages
        (ref: core/consensus/qbft/qbft.go:561)."""
        from charon_tpu.app import k1util

        if not (0 <= m.source < len(self._pubkeys)):
            return False
        if not k1util.verify_bytes(
            self._pubkeys[m.source], qbft.msg_digest(m), m.signature
        ):
            return False
        if check_justification:
            for j in m.justification:
                if not self._verify_msg(j, check_justification=False):
                    return False
        return True

    # -- engine plumbing ---------------------------------------------------

    def _transport(self, duty: Duty) -> qbft.Transport:
        tr = self._instances.get(duty)
        if tr is None:

            async def bcast(msg: qbft.Msg) -> None:
                self._sniff("out", duty, msg)
                # frame carries the sender's trace context so follower
                # nodes' message-handling spans join this duty trace
                from charon_tpu.app.tracer import encode_ctx

                await self.net.broadcast(
                    self.node_idx,
                    duty,
                    msg,
                    dict(self._values.get(duty, {})),
                    tctx=encode_ctx(),
                )

            tr = qbft.Transport(bcast)
            self._instances[duty] = tr
        return tr

    def deliver(
        self,
        duty: Duty,
        msg: qbft.Msg,
        values,
        tctx: str | None = None,
        sender: int | None = None,
    ) -> None:
        """Incoming message from the fabric; values-by-hash cache merge.

        Each received value is re-hashed and inserted only under its
        *recomputed* key, and existing entries are never overwritten — a
        peer cannot bind a decided hash to substituted duty data
        (ref: core/consensus/qbft/qbft.go valuesByHash recomputes).

        `sender` is the CHANNEL identity (the authenticated node index
        the frame arrived from), distinct from msg.source (the signer's
        claim). Nodes only broadcast their own top-level messages, so a
        frame whose source differs from its channel — or whose instance
        differs from the duty it was delivered under — is a replay or
        spoof by the CHANNEL peer: the one attribution the engine itself
        cannot make, because a replayed message carries the original
        (possibly honest) signer's source. Dropped before any engine or
        cache state is touched, evidence named to the channel.

        `tctx` is the sending node's propagated trace context: the
        message-handling span joins the sender's duty trace, which is
        how a follower's consensus work appears in the proposer's
        cross-node timeline. Malformed tctx decodes to None (fresh
        duty-rooted span) — frame corruption never crashes delivery."""
        if self._gater is not None and not self._gater(duty):
            return
        if sender is not None and (
            msg.source != sender or msg.instance != duty
        ):
            if self.evidence is not None:
                self.evidence.record(sender + 1, "qbft_replay")
            return
        from charon_tpu.app.tracer import parse_ctx, span

        with span(
            "qbft.deliver",
            duty=duty,
            tracer=self.tracer,
            remote=parse_ctx(tctx),
            msg_type=getattr(msg.type, "name", str(msg.type)),
            round=msg.round,
            source=msg.source,
        ):
            self._sniff("in", duty, msg)
            # Inbox first: if the sender is over its per-source buffer
            # bound, its value payloads are dropped too — otherwise the
            # cache merge would be an unbounded-memory side channel
            # around the bound.
            if not self._transport(duty).receive(msg):
                return
            cache = self._values.setdefault(duty, {})
            # One honest node contributes one candidate value per
            # instance, so an honest cache never exceeds n entries; cap
            # at 2n.
            max_values = 2 * self.defn.nodes
            for v in values.values():
                if len(cache) >= max_values:
                    break
                try:
                    rh = value_hash(v)
                except Exception:
                    continue
                cache.setdefault(rh, v)

    def _sniff(self, direction: str, duty: Duty, msg: qbft.Msg) -> None:
        import time as _time

        self._sniffer.append(
            {
                # debug-sniffer timestamp: a logging edge operators
                # correlate with wall-clock log lines, never math
                "ts": round(_time.time(), 3),  # lint: allow(monotonic-clock)
                "dir": direction,
                "duty": str(duty),
                "type": getattr(msg.type, "name", str(msg.type)),
                "round": msg.round,
                "source": msg.source,
                "value": (
                    msg.value.hex()[:16]
                    if isinstance(msg.value, bytes)
                    else (str(msg.value)[:16] if msg.value is not None else None)
                ),
                "justification": len(msg.justification or ()),
            }
        )
        mtype = getattr(msg.type, "name", str(msg.type))
        if mtype == "ROUND_CHANGE" and self.on_round_change is not None:
            try:
                self.on_round_change(duty, msg.round, msg.source, direction)
            except Exception:  # noqa: BLE001 — observability must not break delivery
                pass

    def debug_dump(self) -> list[dict]:
        """Recent consensus messages, oldest first (served at
        /debug/consensus; ref: docs/consensus.md:74)."""
        return list(self._sniffer)

    def _ensure_running(self, duty: Duty, value_hash_or_none) -> asyncio.Task:
        task = self._running.get(duty)
        if task is None:
            tr = self._transport(duty)
            task = asyncio.create_task(
                self._run_instance(duty, tr, value_hash_or_none)
            )
            self._running[duty] = task
        return task

    async def _run_instance(self, duty: Duty, tr: qbft.Transport, vhash) -> None:
        import time as _time

        stats: dict = {}
        t0 = _time.monotonic()
        decided_hash = await qbft.run(
            self.defn, tr, duty, self.node_idx, vhash, stats=stats
        )
        if duty in self._decided:
            return
        self._decided.add(duty)
        # decided round + wall duration per timer strategy (ref:
        # consensus metrics ObserveConsensusDuration / SetDecidedRounds
        # labelled by timer type)
        self.last_decided = {
            "duty": duty,
            "round": stats.get("round", 0),
            "duration": _time.monotonic() - t0,
            "timer": self.timer_type,
        }
        if self.on_decided_stats is not None:
            self.on_decided_stats(self.last_decided)
        unsigned_set = self._values.get(duty, {}).get(decided_hash)
        if unsigned_set is None:
            raise RuntimeError(
                f"decided hash with no value in cache for {duty}"
            )
        for sub in self._subs:
            await sub(duty, unsigned_set)

    def trim(self, duty: Duty) -> None:
        """Drop instance state for an expired duty (Deadliner hook)."""
        self._values.pop(duty, None)
        self._instances.pop(duty, None)
        task = self._running.pop(duty, None)
        if task is not None and not task.done():
            task.cancel()
        self._decided.discard(duty)

    # -- workflow API ------------------------------------------------------

    async def propose(self, duty: Duty, unsigned_set: dict[PubKey, object]) -> None:
        """ref: core/consensus/qbft/qbft.go:247 Propose."""
        vhash = value_hash(unsigned_set)
        self._values.setdefault(duty, {})[vhash] = unsigned_set
        task = self._ensure_running(duty, vhash)
        await asyncio.shield(task)

    async def participate(self, duty: Duty) -> None:
        """Join the instance without a proposal
        (ref: core/consensus/qbft/qbft.go:317 Participate)."""
        self._ensure_running(duty, None)
