"""Remote crypto-plane client: a TenantPlane rung ABOVE the local one.

`RemotePlane` is a `TenantPlane` duck type (`t` / `verify` /
`recombine`) that SigAgg / Eth2Verifier / ValidatorAPI wire unchanged.
It dials a `cryptosvc_server` and treats the remote plane as the
PREFERRED rung of the existing degradation ladder — never as a
dependency. The failure contract, in one sentence: on ANY remote
failure the affected jobs run on the local rung (`local` — the node's
own SlotCoalescer / TenantPlane, which itself sits on the tbls ladder)
and duties keep completing.

Failure taxonomy -> behavior:

  * connect refused / handshake failure ... jobs go local immediately
    ("down" state); a supervisor task reconnects on the expbackoff
    schedule (`app/expbackoff.backoff_delay`).
  * heartbeat miss .......................... connection torn down, every
    in-flight job fails over local. Miss detection is pinned to
    `time.monotonic` (injectable `clock`) — a wall-clock step (NTP,
    `testutil/chaos.SkewedClock`) must never fabricate or mask a miss
    (the PR 8 `_arm` bug class, kept out of this new timer surface).
  * mid-flush socket death .................. ditto: the reader fails,
    pending futures get the failure, each waiter degrades locally.
  * malformed / corrupt result frame ........ quarantine strike (the
    configured server address is EXEMPT from mute escalation —
    p2p/quarantine — because a flapping server should cost reconnect
    backoff, not a 300 s mute) and the connection is torn down: after
    payload corruption the stream can't be trusted.
  * server shed (CryptoShed) ................ the job degrades locally;
    the shed is counted per reason.
  * "tbls" error result ..................... NOT a failure: a crypto
    verdict is identical on every rung, so it re-raises as TblsError
    without local retry (same rule as tbls/resilient.ResilientImpl).
  * local in-flight window overflow ......... typed shed: raises
    `PlaneOverloadError` exactly like the in-process service, so the
    submitters' existing catch-sites degrade to their host tbls rung.

Reconnection half-opens the remote rung: exactly ONE in-flight probe
job is allowed through; concurrent jobs stay local until the probe
gets a typed response (result OR shed — either proves the submit path
end to end). A transport failure during the probe drops straight back
to "down".

Cross-process FlushStats attribution: result frames carry the server's
compact stats brief; the client rebases the stage spans onto its own
wall clock, re-roots them on the submitting duty's trace context, and
feeds a synthesized `FlushStats` to `stats_hook` (normally
`app/tracer.plane_span_bridge`), so remote flushes appear in duty
traces exactly like local ones.

Deadlines propagate RELATIVE (seconds remaining at send) and also
bound the client-side wait: a result that can't arrive before the duty
deadline fails over local while the duty is still winnable.
"""

from __future__ import annotations

import asyncio
import random
import time

from charon_tpu.app.expbackoff import Config, backoff_delay
from charon_tpu.core.cryptoplane import FlushStats
from charon_tpu.core.cryptosvc import PlaneOverloadError
from charon_tpu.core.cryptosvc_wire import (
    WIRE_VERSION,
    CryptoChallenge,
    CryptoHeartbeat,
    CryptoHello,
    CryptoHelloAck,
    CryptoResult,
    CryptoShed,
    CryptoSubmit,
    auth_proof,
    read_frame,
    send_frame,
)
from charon_tpu.p2p.codec import CodecError
from charon_tpu.p2p.quarantine import PeerQuarantine
from charon_tpu.tbls import TblsError

# fast reconnect schedule: a crypto-service blip must resolve within a
# slot, not within the p2p default's two-minute cap
RECONNECT_CONFIG = Config(
    base_delay=0.05, multiplier=1.6, jitter=0.2, max_delay=2.0
)


class _RemoteFailure(Exception):
    """Internal: one job's remote attempt failed for `reason` — the
    caller degrades it to the local rung. Never escapes RemotePlane."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


class _Job:
    __slots__ = ("fut", "lanes", "parent")

    def __init__(self, fut, lanes: int, parent):
        self.fut = fut
        self.lanes = lanes
        self.parent = parent  # (trace_id, span_id) | None at submit


class RemotePlane:
    """TenantPlane duck type over a remote crypto-plane service, with
    the local plane as the always-available rung below.

    local: the fallback plane (SlotCoalescer / TenantPlane / anything
    with `t`/`verify`/`recombine`). REQUIRED — the remote service must
    never be a single point of failure.
    """

    def __init__(
        self,
        host: str,
        port: int,
        tenant_id: str,
        auth_token,
        local,
        *,
        heartbeat_timeout: float = 3.0,
        request_timeout: float = 10.0,
        max_inflight_jobs: int = 256,
        max_inflight_lanes: int = 8192,
        backoff_config: Config = RECONNECT_CONFIG,
        rng=None,
        observer=None,  # callable(kind, **fields)
        stats_hook=None,  # callable(FlushStats)
        quarantine: PeerQuarantine | None = None,
        clock=time.monotonic,
        wire: int = WIRE_VERSION,
    ) -> None:
        if local is None:
            raise ValueError(
                "RemotePlane requires a local fallback plane"
            )
        self.host = host
        self.port = port
        self.tenant_id = tenant_id
        self._auth_token = (
            auth_token.encode()
            if isinstance(auth_token, str)
            else bytes(auth_token)
        )
        self._local = local
        self.heartbeat_timeout = heartbeat_timeout
        self.request_timeout = request_timeout
        self.max_inflight_jobs = max_inflight_jobs
        self.max_inflight_lanes = max_inflight_lanes
        self._backoff_cfg = backoff_config
        self._rng = rng or random.Random()
        self.observer = observer
        self.stats_hook = stats_hook
        self.addr = f"{host}:{port}"
        # the configured server address is exempt from mute escalation
        # (ISSUE 17 satellite: flapping server -> backoff, not a mute)
        self.quarantine = quarantine or PeerQuarantine(exempt={self.addr})
        self._clock = clock
        self._wire = wire
        # state: "down" (no usable conn) | "probing" (conn up, one
        # probe in flight allowed) | "up" (full window)
        self.state = "down"
        self._probe_inflight = False
        self._closed = False
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._binary = wire >= 1
        self._heartbeat_interval = 1.0
        self._supervisor: asyncio.Task | None = None
        self._hb_task: asyncio.Task | None = None
        self._reader_task: asyncio.Task | None = None
        self._conn_lost: asyncio.Future | None = None
        self._seq = 0
        self._hb_seq = 0
        self._last_pong = self._clock()
        self._jobs: dict[int, _Job] = {}
        self.inflight_jobs = 0
        self.inflight_lanes = 0
        # observability (scenario tests + app/metrics.remote_hook)
        self.failovers: dict[str, int] = {}
        self.remote_jobs = 0
        self.local_jobs = 0
        self.sheds: dict[str, int] = {}
        self.connects = 0
        self.disconnects: dict[str, int] = {}
        self.reconnect_delays: list[float] = []
        self.remote_t: int | None = None

    # -- TenantPlane surface ----------------------------------------------

    @property
    def t(self) -> int:
        return self._local.t

    async def verify(self, items, deadline: float | None = None):
        items = list(items)
        if not items:
            return []
        res = await self._call(
            "verify", (items,), len(items), deadline
        )
        return list(res)

    async def recombine(
        self,
        pubshares,
        roots,
        partials,
        group_pks,
        indices,
        deadline: float | None = None,
    ):
        rows = (
            list(pubshares),
            list(roots),
            list(partials),
            list(group_pks),
            list(indices),
        )
        if not rows[1]:
            return [], []
        sigs, oks = await self._call(
            "recombine", rows, len(rows[1]), deadline
        )
        return list(sigs), list(oks)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Begin connection supervision. Safe to call once; jobs
        submitted before the first connect simply run local."""
        if self._supervisor is None or self._supervisor.done():
            self._supervisor = asyncio.create_task(self._supervise())

    async def close(self) -> None:
        self._closed = True
        for task in (self._supervisor, self._hb_task):
            if task is not None and not task.done():
                task.cancel()
        tasks = [
            t
            for t in (self._supervisor, self._hb_task)
            if t is not None
        ]
        self._teardown("closed")
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    def _observe(self, kind: str, **fields) -> None:
        if self.observer is not None:
            try:
                self.observer(kind, **fields)
            except Exception:  # noqa: BLE001 — observer bugs stay out
                pass

    # -- connection supervision -------------------------------------------

    async def _supervise(self) -> None:
        retries = 0
        while not self._closed:
            try:
                await self._connect_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — any dial/handshake
                # fault lands here; the schedule below is the retry
                self._observe(
                    "connect_fail",
                    error=f"{type(e).__name__}",
                )
                delay = backoff_delay(
                    self._backoff_cfg, retries, self._rng
                )
                retries += 1
                self.reconnect_delays.append(delay)
                await asyncio.sleep(delay)
                continue
            retries = 0
            self.connects += 1
            self._observe("connect")
            conn_lost = self._conn_lost
            if conn_lost is not None:
                await conn_lost  # resolved by _teardown(reason)

    async def _connect_once(self) -> None:
        reader, writer = await asyncio.open_connection(
            self.host, self.port
        )
        try:
            challenge = await asyncio.wait_for(
                read_frame(reader), self.request_timeout
            )
            if not isinstance(challenge, CryptoChallenge):
                raise CodecError("expected CryptoChallenge")
            proof = auth_proof(self._auth_token, challenge.nonce)
            hello = CryptoHello(self.tenant_id, proof, self._wire)
            # the proof is an HMAC digest, not the token; the token
            # itself never crosses the wire
            send_frame(writer, hello, False)  # lint: allow(secret-flow)
            await writer.drain()
            ack = await asyncio.wait_for(
                read_frame(reader), self.request_timeout
            )
            if not isinstance(ack, CryptoHelloAck) or not ack.ok:
                raise ConnectionError("service hello rejected")
        except BaseException:
            writer.close()
            raise
        self._reader = reader
        self._writer = writer
        self._binary = min(self._wire, ack.wire) >= 1
        # the server echoes every ping on receipt, so pong freshness is
        # bounded by OUR ping cadence: never ping slower than a third of
        # the liveness budget, or a timeout tighter than the server's
        # advertised interval would flap on every single beat
        self._heartbeat_interval = max(
            0.05, min(float(ack.heartbeat), self.heartbeat_timeout / 3.0)
        )
        self.remote_t = ack.t or None
        self._last_pong = self._clock()
        self._conn_lost = asyncio.get_running_loop().create_future()
        self.state = "probing"
        self._probe_inflight = False
        self._observe("state", state=self.state)
        self._reader_task = asyncio.create_task(self._read_loop())
        self._hb_task = asyncio.create_task(self._heartbeat_loop())

    def _teardown(self, reason: str, reader=None) -> None:
        """Drop the connection (idempotent): fail in-flight jobs over
        to their waiters' local fallback and wake the supervisor.
        `reader` guards against a STALE read loop (its socket died
        after a reconnect already succeeded) tearing down the fresh
        connection."""
        if reader is not None and reader is not self._reader:
            return
        if self._writer is not None:
            self._writer.close()
        self._reader = None
        self._writer = None
        if self._hb_task is not None and not self._hb_task.done():
            self._hb_task.cancel()
        if self.state != "down":
            self.state = "down"
            self.disconnects[reason] = (
                self.disconnects.get(reason, 0) + 1
            )
            self._observe("disconnect", reason=reason)
            self._observe("state", state=self.state)
        self._probe_inflight = False
        for job in list(self._jobs.values()):
            if not job.fut.done():
                job.fut.set_exception(_RemoteFailure(reason))
        self._jobs.clear()
        if self._conn_lost is not None and not self._conn_lost.done():
            self._conn_lost.set_result(None)

    # -- heartbeats (time.monotonic ONLY) ---------------------------------

    def _heartbeat_expired(self) -> bool:
        """Pure check, injectable clock: True when the last echo is
        older than heartbeat_timeout on the MONOTONIC clock."""
        return (
            self._clock() - self._last_pong > self.heartbeat_timeout
        )

    async def _heartbeat_loop(self) -> None:
        while not self._closed:
            writer = self._writer
            if writer is None:
                return
            self._hb_seq += 1
            try:
                send_frame(
                    writer,
                    CryptoHeartbeat(self._hb_seq),
                    self._binary,
                )
                await writer.drain()
            except (ConnectionError, OSError, RuntimeError):
                self._teardown("io")
                return
            await asyncio.sleep(self._heartbeat_interval)
            if self._heartbeat_expired():
                self._observe("heartbeat_miss")
                self._teardown("heartbeat")
                return

    # -- read loop ---------------------------------------------------------

    async def _read_loop(self) -> None:
        reader = self._reader
        while reader is not None and reader is self._reader:
            try:
                msg = await read_frame(reader)
            except CodecError:
                # corrupt result frame: strike (the pinned server addr
                # never escalates to a mute) and drop the stream — the
                # framing can't be trusted after payload corruption
                self.quarantine.strike(self.addr)
                self._teardown("codec", reader=reader)
                return
            except (
                ConnectionError,
                asyncio.IncompleteReadError,
                OSError,
            ):
                self._teardown("io", reader=reader)
                return
            self.quarantine.forgive(self.addr)
            if isinstance(msg, CryptoHeartbeat):
                if msg.echo:
                    self._last_pong = self._clock()
                continue
            if isinstance(msg, CryptoResult):
                self._on_result(msg)
            elif isinstance(msg, CryptoShed):
                self._on_shed(msg)
            # unknown-but-valid frames: ignore (forward compat)

    def _probe_settled(self) -> None:
        """Any typed response proves the submit path end to end."""
        if self.state == "probing":
            self.state = "up"
            self._observe("state", state=self.state)

    def _on_result(self, msg: CryptoResult) -> None:
        self._probe_settled()
        job = self._jobs.pop(msg.job_id, None)
        if job is None:
            return  # late result for a timed-out/failed-over job
        if msg.error_kind == "tbls":
            # crypto verdict — identical on every rung; do NOT fail over
            if not job.fut.done():
                job.fut.set_exception(TblsError(msg.error))
            return
        if msg.error_kind:
            if not job.fut.done():
                job.fut.set_exception(_RemoteFailure("remote_error"))
            return
        if msg.stats is not None:
            self._bridge_stats(msg.stats, job)
        if not job.fut.done():
            job.fut.set_result(msg.value)

    def _on_shed(self, msg: CryptoShed) -> None:
        self._probe_settled()
        self.sheds[msg.reason] = self.sheds.get(msg.reason, 0) + 1
        self._observe("remote_shed", reason=msg.reason)
        job = self._jobs.pop(msg.job_id, None)
        if job is not None and not job.fut.done():
            job.fut.set_exception(_RemoteFailure("shed"))

    def _bridge_stats(self, brief: dict, job: _Job) -> None:
        """Rebase the server's flush brief onto this host's wall clock
        and feed it to the local tracer bridge, rooted on the
        submitting duty's trace context."""
        if self.stats_hook is None or not isinstance(brief, dict):
            return
        now = time.time()  # lint: allow(monotonic-clock) — attribution spans are wall-timestamped

        def span(rel):
            if not rel:
                return None
            try:
                return (now - float(rel[0]), now - float(rel[1]))
            except (TypeError, ValueError, IndexError):
                return None

        try:
            stats = FlushStats(
                jobs=int(brief.get("jobs", 1)),
                lanes=int(brief.get("lanes", job.lanes)),
                flush_seconds=float(brief.get("flush_seconds", 0.0)),
                window=float(brief.get("window", 0.0)),
                inflight=int(brief.get("inflight", 1)),
                pad_lanes=None,
                padded_lanes=None,
                decode_queue_seconds=(),
                fallback=bool(brief.get("fallback", False)),
                decode_mode=str(brief.get("decode_mode", "remote")),
                pack_span=span(brief.get("pack_rel")),
                device_span=span(brief.get("device_rel")),
                parents=(job.parent,) if job.parent else (),
                tenant_lanes=(
                    (
                        self.tenant_id,
                        int(brief.get("tenant_lanes", job.lanes)),
                    ),
                ),
            )
            self.stats_hook(stats)
        except Exception:  # noqa: BLE001 — attribution is best-effort;
            pass  # a malformed brief must never fail the job

    # -- job routing -------------------------------------------------------

    def _remote_usable(self) -> bool:
        if self._writer is None or self._closed:
            return False
        if self.state == "up":
            return True
        return self.state == "probing" and not self._probe_inflight

    async def _call(self, kind, args, lanes, deadline):
        if not self._remote_usable():
            reason = (
                "probing" if self.state == "probing" else "down"
            )
            return await self._run_local(kind, args, deadline, reason)
        if self.inflight_jobs + 1 > self.max_inflight_jobs:
            self._shed_local("jobs", lanes)
        if self.inflight_lanes + lanes > self.max_inflight_lanes:
            self._shed_local("lanes", lanes)
        probe = self.state == "probing"
        if probe:
            self._probe_inflight = True
        try:
            return await self._round_trip(kind, args, lanes, deadline)
        except _RemoteFailure as e:
            return await self._run_local(
                kind, args, deadline, e.reason
            )
        finally:
            if probe:
                self._probe_inflight = False

    def _shed_local(self, reason: str, lanes: int):
        """Typed shed on in-flight window overflow: same contract as
        the in-process service, so submitters' PlaneOverloadError
        catch-sites degrade to their own host rung."""
        self._observe("shed", reason=reason, lanes=lanes)
        raise PlaneOverloadError(
            self.tenant_id,
            reason,
            f"remote window {self.inflight_jobs} jobs / "
            f"{self.inflight_lanes} lanes in flight (+{lanes})",
        )

    async def _round_trip(self, kind, args, lanes, deadline):
        writer = self._writer
        if writer is None:
            raise _RemoteFailure("down")
        loop = asyncio.get_running_loop()
        self._seq += 1
        job_id = self._seq
        parent = None
        try:
            from charon_tpu.app.tracer import current_ctx

            parent = current_ctx()
        except Exception:  # noqa: BLE001 — tracing is optional
            parent = None
        fut = loop.create_future()
        # the waiter can stop listening first (wait_for timeout racing a
        # teardown that fails the job over) — mark any late exception
        # retrieved so abandoned futures don't log spurious warnings
        fut.add_done_callback(
            lambda f: None if f.cancelled() else f.exception()
        )
        job = _Job(fut, lanes, parent)
        self._jobs[job_id] = job
        self.inflight_jobs += 1
        self.inflight_lanes += lanes
        try:
            deadline_rel = (
                # duty deadlines are wall-clock by plane contract; only
                # the RELATIVE remainder crosses the wire
                None if deadline is None else deadline - time.time()  # lint: allow(monotonic-clock)
            )
            try:
                send_frame(
                    writer,
                    CryptoSubmit(
                        job_id, kind, args, lanes, deadline_rel
                    ),
                    self._binary,
                )
                await writer.drain()
            except (ConnectionError, OSError, RuntimeError):
                self._teardown("io")
                raise _RemoteFailure("io") from None
            timeout = self.request_timeout
            if deadline_rel is not None:
                # never wait past the duty deadline: fail over while
                # the local rung can still win the duty
                timeout = min(
                    timeout, max(0.05, deadline_rel) + 0.25
                )
            try:
                value = await asyncio.wait_for(job.fut, timeout)
            except asyncio.TimeoutError:
                raise _RemoteFailure("timeout") from None
        finally:
            self._jobs.pop(job_id, None)
            self.inflight_jobs -= 1
            self.inflight_lanes -= lanes
        self.remote_jobs += 1
        return value

    async def _run_local(self, kind, args, deadline, reason: str):
        self.local_jobs += 1
        self.failovers[reason] = self.failovers.get(reason, 0) + 1
        lanes = len(args[0]) if kind == "verify" else len(args[1])
        self._observe("failover", reason=reason, lanes=lanes)
        if kind == "verify":
            return await self._local.verify(
                args[0], deadline=deadline
            )
        return await self._local.recombine(*args, deadline=deadline)
