"""Core duty workflow: the business logic of the distributed validator.

Mirrors the reference's core layer (ref: core/interfaces.go — ten
components stitched by core.Wire) re-designed for asyncio + batch-first
crypto: immutable frozen-dataclass values flow through async pub/sub
subscriptions, and every signature-heavy step hands whole duty-sets to the
batched tbls backend instead of per-signature calls.

Components (ref SURVEY.md §2.1):
  types/eth2data  abstract value types (Duty, UnsignedData, SignedData)
  deadline        duty-expiry engine
  scheduler       slot ticker + duty resolution
  fetcher         duty input data from the beacon node
  consensus       pluggable consensus (QBFT)
  dutydb          blocking unsigned-data store
  validatorapi    beacon-API server for the downstream VC
  parsigdb        partial-signature store w/ threshold grouping
  parsigex        partial-signature exchange between peers
  sigagg          batched threshold aggregation
  aggsigdb        aggregated-signature store
  bcast           broadcast to the beacon node
  tracker         per-duty failure analysis
"""

from charon_tpu.core.types import (  # noqa: F401
    Duty,
    DutyType,
    PubKey,
)
