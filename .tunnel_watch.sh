#!/bin/bash
# Watch for the axon relay tunnel; when it answers: prime + measure the
# headline bench over a wide batch range (largest first), then the
# slot-step bench. Never kill these mid-compile.
# Round 4: logs to bench_r4_auto.log / results to bench_r4_auto.out.
# Also drops a timestamped probe line every ~15 min so a tunnel-dead
# round has an auditable post-mortem trail (VERDICT r3 next-step 1).
log=/root/repo/bench_r4_auto.log
# single source of truth for the relay probe port: bench_common.py
port=$(cd /root/repo && python -c 'import bench_common; print(bench_common.RELAY_PROBE_PORT)')
echo "[watch $(date +%H:%M:%S)] start (round 4), probing port $port" >> "$log"
n=0
while true; do
  if timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/$port" 2>/dev/null; then
    echo "[watch $(date +%H:%M:%S)] port $port OPEN - launching bench" >> "$log"
    break
  fi
  n=$((n+1))
  if [ $((n % 20)) -eq 0 ]; then
    echo "[watch $(date +%H:%M:%S)] port $port still refusing connect (probe $n)" >> "$log"
  fi
  sleep 45
done
sleep 5
cd /root/repo
BENCH_BATCHES="4096 2048 1024 512 256" python bench.py >> /root/repo/bench_r4_auto.out 2>> "$log"
echo "[watch $(date +%H:%M:%S)] bench exited rc=$?" >> "$log"
python bench_slotstep.py >> /root/repo/bench_r4_auto.out 2>> "$log"
echo "[watch $(date +%H:%M:%S)] slotstep exited rc=$?" >> "$log"
