"""Headline benchmark: batched BLS12-381 signature verification throughput.

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
Progress heartbeats go to stderr so the driver sees liveness without
polluting the parseable output.

Metric matches BASELINE.json ("batched BLS verify sigs/sec"): the hot path
the reference executes one herumi C++ call at a time
(ref: core/validatorapi/validatorapi.go:1213 partial-sig verify,
core/parsigex/parsigex.go:94-98 peer-sig verify). Here a whole batch runs
as one XLA program on the accelerator.

Verification kernel: GROUPED random-linear-combination batch verification
(ops/pairing.py batched_verify_grouped_rlc) — lanes sharing a message
collapse into one Miller pair per distinct message (plus one aggregate
pair) under per-lane 64-bit random exponents, with ONE shared final
exponentiation (2^-64 soundness; on a False the caller re-runs the
per-lane kernel to attribute — exactly the strategy consensus clients
use for gossip batches, and the same message-sharing structure a DV
cluster sees: every validator in a committee signs the same attestation
data). The workload here is all-valid, so the batch must verify True.

Budget discipline (round-1 bench timed out, VERDICT Weak #1):
  * the workload is generated on host by the native C++ backend
    (milliseconds) — the device only runs the verify kernel;
  * ONE kernel is compiled per attempted batch size, after a tiny warmup
    batch; the persistent cache (.jax_cache, primed on this platform)
    makes the steady-state run seconds;
  * batch sizes sweep ASCENDING and the best completed measurement is
    banked as each size finishes — a short live-tunnel window still
    yields one TPU line, a size whose program crashes the compiler is
    skipped, and a mid-sweep device wedge emits the banked best via the
    result guard instead of hanging (the guard only arms off-CPU: on
    the CPU fallback a long pause is just compile time);
  * every phase heartbeats with elapsed time.

vs_baseline: measured device throughput divided by the single-threaded
herumi-class CPU reference rate from BASELINE.md (the reference publishes
no numbers — BASELINE.json.published == {} — so we use the well-known
~1.5 ms/verify herumi envelope as the denominator; see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

# Single-signature BLS verify on a modern CPU core with herumi/BLST-class
# C++ (the reference's backend): ~1.5 ms => ~666 sigs/sec.
CPU_REFERENCE_SIGS_PER_SEC = 666.0

WARMUP_BATCH = 4
ITERS = 3


def pick_batches(platform: str) -> list[int]:
    """Explicit BENCH_BATCHES always wins. Otherwise: the TPU profile
    sweeps real sizes; the CPU-fallback profile (tunnel dead) runs one
    small cached shape — XLA:CPU compiles of the big pairing program
    take tens of minutes on this 1-core VM and the number is a
    liveness/honesty datapoint, not the headline."""
    tunnel_fallback = bool(os.environ.get("CHARON_BENCH_TUNNEL"))
    if "BENCH_BATCHES" in os.environ and not (platform == "cpu" and tunnel_fallback):
        return [int(b) for b in os.environ["BENCH_BATCHES"].split()]
    if platform != "cpu":
        # ASCENDING sweep (VERDICT r4 next-step 2): the smallest size
        # compiles/runs first so even a short live-tunnel window banks
        # one driver-format TPU line; larger sizes then improve on it
        # and the best throughput is reported. A wedge mid-sweep emits
        # the banked best instead of hanging (result guard below).
        return [256, 1024, 4096]
    # a BENCH_BATCHES meant for the TPU sweep must not leak through the
    # dead-tunnel CPU re-exec: batch 4096 on XLA:CPU compiles for hours
    return [int(b) for b in os.environ.get("BENCH_BATCHES_CPU", "16").split()]

T0 = time.perf_counter()


def hb(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def main() -> None:
    if os.environ.get("CHARON_BENCH_TEST_CRASH") == "1":
        # test hook: simulate the persistent-cache segfault so the
        # supervisor's crash handling stays covered (tests/test_bench_supervisor.py)
        import signal

        os.kill(os.getpid(), signal.SIGSEGV)

    from bench_common import init_jax_with_watchdog

    jax = init_jax_with_watchdog("batched_bls_verify", "sigs/sec")
    platform = jax.devices()[0].platform
    batches = pick_batches(platform)
    hb(f"jax up, platform={platform}, devices={jax.devices()}, batches={batches}")

    from charon_tpu.crypto import h2c
    from charon_tpu.crypto.g1g2 import g1_from_bytes, g2_from_bytes
    from charon_tpu.ops import curve as C
    from charon_tpu.ops import limb
    from charon_tpu.ops import pairing as DP

    ctx = limb.default_fp_ctx()
    fr_ctx = limb.default_fr_ctx()
    hb(f"modules imported, ctx={ctx.name}")

    # Workload on host via the native C++ backend (ref-equivalent herumi
    # role). Distinct messages per lane come from a small message pool.
    try:
        from charon_tpu.tbls.native_impl import NativeImpl

        impl = NativeImpl()
    except Exception as e:  # pure-Python fallback (slower host setup)
        hb(f"native backend unavailable ({e}); python fallback")
        from charon_tpu.tbls.python_impl import PythonImpl

        impl = PythonImpl()

    n_msgs = 8
    msgs_raw = [b"bench-msg-%d" % i for i in range(n_msgs)]
    msg_pts = [h2c.hash_to_g2(m) for m in msgs_raw]

    rng = random.Random(2026)
    nmax = max(batches)
    sks = [rng.randrange(1, 2**250).to_bytes(32, "big") for _ in range(nmax)]
    pks = [impl.secret_to_public_key(sk) for sk in sks]
    sigs = [impl.sign(sk, msgs_raw[i % n_msgs]) for i, sk in enumerate(sks)]
    hb(f"host workload built: {nmax} keys/sigs")

    def pack(npack):
        """[M, K] grouped layout: lane i signed message i % n_msgs, so
        group m holds lanes m, m+n_msgs, m+2*n_msgs, ..."""
        import numpy as np

        m = min(n_msgs, npack)
        k = npack // m
        # lane index for group g, slot j is j*n_msgs + g in the original
        # round-robin assignment (sig[i] covers msgs_raw[i % n_msgs])
        order = [j * n_msgs + g for g in range(m) for j in range(k)]
        pk = C.g1_pack(ctx, [g1_from_bytes(pks[i]) for i in order])
        pk = jax.tree_util.tree_map(lambda a: a.reshape(m, k, -1), pk)
        sig = C.g2_pack(ctx, [g2_from_bytes(sigs[i]) for i in order])
        sig = jax.tree_util.tree_map(lambda a: a.reshape(m, k, -1), sig)
        msg = C.g2_pack(ctx, msg_pts[:m])
        rand = jax.numpy.asarray(
            np.asarray(
                limb.ctx_pack(
                    fr_ctx,
                    [rng.randrange(1, 1 << 64) for _ in range(m * k)],
                )
            ).reshape(m, k, -1)
        )
        return pk, msg, sig, rand

    def make_kernel():
        return jax.jit(
            lambda pk, msg, sig, r: DP.batched_verify_grouped_rlc(
                ctx, fr_ctx, pk, msg, sig, r
            )
        )

    # degradation ladder: fused-fp2 pallas -> plain mont pallas -> pure
    # XLA. Each rung re-jits once; a Mosaic regression in the newest
    # kernel family only costs its own speedup, not the whole fast path.
    from charon_tpu.ops import fptower as FT

    # BENCH_MXU=1: A/B the int8-MXU mont_mul decomposition
    # (ops/limb_mxu.py) — fp2 fusion off so every multiply actually
    # routes through the Toeplitz-matmul lowering
    bench_mxu = os.environ.get("BENCH_MXU") == "1"
    if bench_mxu and ctx.limb_bits != 12:
        # the decomposition only exists for the 12-bit geometry (the
        # CPU-fallback profile uses 24-bit limbs) — measuring here would
        # present the plain kernel as an MXU number
        hb(
            f"BENCH_MXU=1 ignored: ctx {ctx.name} has {ctx.limb_bits}-bit "
            "limbs, no MXU lowering"
        )
        bench_mxu = False
    if bench_mxu:
        hb("BENCH_MXU=1: int8-MXU mont_mul lowering active, fp2 fusion off")
        limb.set_mxu(True)
        FT.set_fp2_fusion(False)

    from charon_tpu.ops import msm as MSM

    def _rung_msm_off():
        MSM.set_msm(False)

    def _rung_fp2_off():
        FT.set_fp2_fusion(False)

    def _rung_pallas_off():
        limb.set_pallas(False)

    def _rung_mxu_off():
        limb.set_mxu(False)

    # under BENCH_MXU the fp2-fusion rung would rebuild a byte-identical
    # kernel (fusion is already off), but pallas-off stays meaningful:
    # once mxu steps down, mont_mul dispatches to the Pallas kernel and
    # a Mosaic regression there still needs the pure-XLA floor
    # "without msm" first: the Pippenger randomization stage is the
    # newest kernel family — a compiler regression there falls back to
    # the proven per-lane double-and-add (the round-4 1664 sigs/s path)
    # deploy-pinned env overrides (CHARON_MSM=0 etc., e.g. the TPU-watch
    # msm_off gate): the ops hot paths no longer read the environment,
    # so the baseline must re-assert them itself (core/autotune owns the
    # fold-in; absent vars resolve to None = kernel default)
    from charon_tpu.core.autotune import env_overrides

    _env_pins = env_overrides()

    def apply_baseline():
        """Restore the full fast path. Called before every batch attempt
        so a SIZE-induced failure (e.g. OOM at 16384) cannot burn rungs
        that then silently degrade the smaller batch's measurement."""
        MSM.set_msm(_env_pins.get("msm"))
        limb.set_pallas(None)
        if bench_mxu:
            limb.set_mxu(True)
            FT.set_fp2_fusion(False)
        else:
            limb.set_mxu(_env_pins.get("mxu_mont"))
            FT.set_fp2_fusion(True)

    def fresh_rungs():
        return (
            [
                ("without msm", _rung_msm_off),
                ("without mxu", _rung_mxu_off),
                ("without pallas", _rung_pallas_off),
            ]
            if bench_mxu
            else [
                ("without msm", _rung_msm_off),
                ("without fp2 fusion", _rung_fp2_off),
                ("without pallas", _rung_pallas_off),
            ]
        )

    state = {"kernel": make_kernel(), "rungs": fresh_rungs(), "used": []}

    def reset_ladder():
        apply_baseline()
        state["kernel"] = make_kernel()
        state["rungs"] = fresh_rungs()
        state["used"] = []

    def run_verify(args, label: str):
        """Run the kernel; on failure step down the degradation ladder
        and retry; re-raise once out of rungs so the caller can fall
        through to a smaller batch."""
        while True:
            try:
                t = time.perf_counter()
                ok = state["kernel"](*args)
                ok.block_until_ready()
                hb(f"{label} compile+run {time.perf_counter() - t:.1f}s")
                break
            except Exception as e:
                if not state["rungs"]:
                    raise
                rung_name, apply = state["rungs"].pop(0)
                hb(
                    f"{label} failed ({type(e).__name__}: {str(e)[:120]}); "
                    f"retrying {rung_name}"
                )
                apply()
                state["used"].append(rung_name)
                state["kernel"] = make_kernel()
        assert bool(ok), f"{label} batch verification failed"
        return ok

    def result_json(sigs_per_sec, batch, degraded, sweep):
        out = {
            "metric": "batched_bls_verify",
            "value": round(sigs_per_sec, 2),
            "unit": "sigs/sec",
            "vs_baseline": round(sigs_per_sec / CPU_REFERENCE_SIGS_PER_SEC, 4),
            "platform": platform,
            "batch": batch,
        }
        if degraded:
            # rungs burned while measuring THIS batch — the number is a
            # degraded-path measurement, never silently presented as the
            # full fast path
            out["degraded"] = degraded
        if len(sweep) > 1:
            out["sweep"] = {str(b): round(v, 2) for b, v in sweep.items()}
        tunnel_state = os.environ.get("CHARON_BENCH_TUNNEL", "")
        if tunnel_state:
            out["note"] = (
                f"TPU tunnel {tunnel_state}; XLA:CPU fallback measurement "
                "on a 1-core VM, not the TPU headline (see PERF.md)"
            )
        return json.dumps(out)

    # Result guard: bank the best measurement so far; if a later, larger
    # batch wedges the device (round-4 post-mortem: claims/dispatches can
    # hang minutes after a clean run), a watchdog emits the banked line
    # and exits instead of leaving the driver with nothing. The deadline
    # is pushed forward before each phase.
    import threading

    guard = {"deadline": None, "banked": None}
    per_size_budget = float(os.environ.get("CHARON_BENCH_SIZE_BUDGET", 900))
    # The stall guard defends against the TPU tunnel wedging mid-bench
    # (a dispatch that never returns). On the CPU platform the claim has
    # already succeeded and nothing can wedge — a long pause is just
    # XLA:CPU compile time on a 1-core host, and killing it produced a
    # spurious 0.0 line in rehearsal. Never arm the guard on CPU.
    guard_active = platform != "cpu"

    def _guard_loop():
        while True:
            time.sleep(5)
            dl = guard["deadline"]
            if dl is not None and time.perf_counter() > dl:
                if guard["banked"] is not None:
                    hb("phase deadline passed; emitting banked best result")
                    print(guard["banked"], flush=True)
                    os._exit(0)
                # nothing banked: the device wedged before any batch
                # completed. Follow the full claim ladder — a fresh TPU
                # claim inside the budget, the CPU-pinned re-exec past
                # it; the error line only if re-exec itself fails.
                from bench_common import claim_retry_env

                try:
                    attempt = int(
                        os.environ.get("CHARON_BENCH_CLAIM_ATTEMPT", "1")
                    )
                except ValueError:
                    attempt = 1  # malformed env must not kill the guard
                updates = claim_retry_env(attempt)
                hb(
                    "phase deadline passed with nothing banked: "
                    + (
                        "re-exec for a fresh claim"
                        if "CHARON_BENCH_CLAIM_ATTEMPT" in updates
                        else "claim budget exhausted"
                    )
                )
                # apply the ladder's updates in BOTH cases: a fresh TPU
                # attempt inside the budget, or the CPU pin past it —
                # the pinned re-exec still produces a real CPU-fallback
                # measurement instead of a 0.0 line
                os.environ.update(updates)
                try:
                    os.execv(sys.executable, [sys.executable] + sys.argv)
                except OSError:
                    pass
                print(
                    json.dumps(
                        {
                            "metric": "batched_bls_verify",
                            "value": 0.0,
                            "unit": "sigs/sec",
                            "vs_baseline": 0.0,
                            "error": "device stalled mid-bench before "
                            "any batch completed, and re-exec failed",
                        }
                    ),
                    flush=True,
                )
                os._exit(0)

    threading.Thread(target=_guard_loop, daemon=True).start()

    def arm_guard():
        if guard_active:
            guard["deadline"] = time.perf_counter() + per_size_budget

    # tiny warmup shape first: proves the pipeline end-to-end before the
    # big compiles. TPU only — on the CPU fallback every shape is a full
    # extra pairing-program compile (~8 min at opt-0 on a 1-core host)
    # and the single small fallback batch needs no pipeline proof.
    if platform != "cpu":
        arm_guard()
        run_verify(pack(WARMUP_BATCH), f"warmup batch={WARMUP_BATCH}")

    best = None  # (sigs_per_sec, batch, degraded)
    sweep: dict[int, float] = {}
    for attempt in batches:
        try:
            # actual verified lane count: pack() lays lanes out [M, K]
            # with K = attempt // n_msgs, so a non-multiple batch would
            # otherwise silently verify fewer sigs than reported
            actual = min(n_msgs, attempt) * (attempt // min(n_msgs, attempt))
            reset_ladder()
            packed = pack(attempt)
            arm_guard()
            run_verify(packed, f"main batch={actual}")
            kernel = state["kernel"]
            times = []
            for i in range(ITERS):
                arm_guard()
                t = time.perf_counter()
                kernel(*packed).block_until_ready()
                times.append(time.perf_counter() - t)
                hb(f"batch={actual} iter {i}: {times[-1]:.3f}s")
            sigs_per_sec = actual / min(times)
            sweep[actual] = sigs_per_sec
            hb(
                f"batch={actual} best {min(times):.3f}s -> "
                f"{sigs_per_sec:.0f} sigs/sec"
            )
            if best is None or sigs_per_sec > best[0]:
                best = (sigs_per_sec, actual, list(state["used"]))
            guard["banked"] = result_json(best[0], best[1], best[2], sweep)
        except AssertionError:
            raise  # verification failing is a correctness bug, not size
        except Exception as e:
            hb(
                f"batch={attempt} unusable ({type(e).__name__}: "
                f"{str(e)[:100]}); continuing sweep"
            )
    guard["deadline"] = None
    if best is None:
        raise RuntimeError("no batch size compiled successfully")
    print(result_json(best[0], best[1], best[2], sweep))


def _supervise() -> int:
    """Run main() in a CHILD process and guarantee exactly one JSON line
    on stdout even if the child SEGFAULTS — this image's jax
    persistent-cache serialization crashes the process occasionally
    (CI.md "Known environment flake"), and a signal death would
    otherwise leave the driver with no parseable line at all. A crashed
    child is retried once (re-running recompiles past a corrupt cache
    entry and recovers), then reported as an error line."""
    import subprocess

    env = {**os.environ, "CHARON_BENCH_CHILD": "1"}
    last_rc = 0
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            text=True,  # stderr passes through: driver sees heartbeats
        )
        json_lines = [
            line
            for line in (proc.stdout or "").splitlines()
            if line.startswith("{")
        ]
        if json_lines:
            print(json_lines[-1])
            return 0
        last_rc = proc.returncode
        hb(f"bench child died rc={last_rc} with no JSON (attempt {attempt})")
    print(
        json.dumps(
            {
                "metric": "batched_bls_verify",
                "value": 0.0,
                "unit": "sigs/sec",
                "vs_baseline": 0.0,
                "error": f"bench child crashed twice (rc={last_rc}) "
                "without emitting a result",
            }
        )
    )
    return 0


if __name__ == "__main__":
    if os.environ.get("CHARON_BENCH_CHILD") != "1":
        sys.exit(_supervise())
    try:
        main()
    except Exception as e:  # always emit one parseable line
        print(
            json.dumps(
                {
                    "metric": "batched_bls_verify",
                    "value": 0.0,
                    "unit": "sigs/sec",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        sys.exit(0)
