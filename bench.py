"""Headline benchmark: batched BLS12-381 signature verification throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric matches BASELINE.json ("batched BLS verify sigs/sec"): the hot path
the reference executes one herumi C++ call at a time
(ref: core/validatorapi/validatorapi.go:1213 partial-sig verify,
core/parsigex/parsigex.go:94-98 peer-sig verify). Here a whole batch runs
as one XLA program on the accelerator.

vs_baseline: measured device throughput divided by the single-threaded
herumi-class CPU reference rate from BASELINE.md (the reference publishes
no numbers — BASELINE.json.published == {} — so we use the well-known
~1.5 ms/verify herumi envelope as the denominator; see BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time


# Single-signature BLS verify on a modern CPU core with herumi/BLST-class
# C++ (the reference's backend): ~1.5 ms => ~666 sigs/sec.
CPU_REFERENCE_SIGS_PER_SEC = 666.0

BATCH = 1024
WARMUP = 1
ITERS = 3


def main() -> None:
    import jax

    # Persistent compilation cache: kernels compiled once (here or in CI)
    # are reused across processes — the steady-state deployment shape.
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from charon_tpu.crypto import bls, h2c
    from charon_tpu.ops import curve as C
    from charon_tpu.ops import limb
    from charon_tpu.ops import pairing as DP

    ctx = limb.default_fp_ctx()
    fr_ctx = limb.default_fr_ctx()

    # Build a verify workload entirely from public material. Signatures are
    # generated on-device (dogfooding the batched scalar-mul kernel) to
    # keep host bigint work out of the setup path.
    import random

    rng = random.Random(2026)
    from charon_tpu.crypto.fields import R
    from charon_tpu.ops import blsops

    engine = blsops.BlsEngine(ctx, fr_ctx)
    n_msgs = 8
    msg_pts = [h2c.hash_to_g2(b"bench-%d" % i) for i in range(n_msgs)]
    sks = [rng.randrange(1, R) for _ in range(BATCH)]
    from charon_tpu.crypto.g1g2 import G1_GEN

    pks = engine.g1_scalar_mul_batch([G1_GEN] * BATCH, sks)
    msgs = [msg_pts[i % n_msgs] for i in range(BATCH)]
    sigs = engine.g2_scalar_mul_batch(msgs, sks)

    pk = C.g1_pack(ctx, pks)
    msg = C.g2_pack(ctx, msgs)
    sig = C.g2_pack(ctx, sigs)

    kernel = jax.jit(lambda p, m, s: DP.batched_verify(ctx, p, m, s))

    for _ in range(WARMUP):
        ok = kernel(pk, msg, sig)
        ok.block_until_ready()
    assert bool(ok.all()), "bench workload failed verification"

    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        kernel(pk, msg, sig).block_until_ready()
        times.append(time.perf_counter() - t0)

    best = min(times)
    sigs_per_sec = BATCH / best
    print(
        json.dumps(
            {
                "metric": "batched_bls_verify",
                "value": round(sigs_per_sec, 2),
                "unit": "sigs/sec",
                "vs_baseline": round(sigs_per_sec / CPU_REFERENCE_SIGS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit one parseable line
        print(
            json.dumps(
                {
                    "metric": "batched_bls_verify",
                    "value": 0.0,
                    "unit": "sigs/sec",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        sys.exit(0)
