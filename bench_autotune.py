#!/usr/bin/env python
"""bench_autotune.py — kernel auto-tuner + AOT compile-artifact gates
(ISSUE 18 acceptance).

Measures the two boot paths `core/autotune.resolve` gives a node and
FAILS (exit 1) when either regresses:

  * COLD — no profile on disk: `resolve("force")` micro-benches the
    candidate axes on the bucket-ladder shape, persists the profile,
    then `aot_prewarm` pushes the chosen variants through the
    persistent compilation cache (jaxcache.py).
  * WARM — profile + seeded cache: `resolve("auto")` must be a PURE
    profile load (outcome "hit", zero bench runs, zero new cache
    entries) and the warm wall must come in under --assert-warm-frac
    (default 0.10) of the cold wall — the seconds-not-minutes fleet
    cold-start gate. Remeasured once before a verdict (CI-noise
    discipline).

What the warm fraction covers is geometry-dependent
(--warm-frac-scope): the TUNE step (micro-bench + persist vs pure
profile load) amortizes everywhere — measured ~40,000x on the CI
geometry — and `--smoke` gates THAT at < 10%. The full boot wall
(tune + prewarm, scope `boot`, the non-smoke default) additionally
pays one re-TRACE per prewarmed program on every boot; the persistent
cache removes only the XLA-compile term. On the 1-core opt-0 XLA:CPU
CI geometry trace ~= compile (~40 s each for the 4-lane recombine), so
the boot-scope ratio floors near 35% REGARDLESS of artifact reuse —
the < 10% boot gate is meaningful exactly where compile dominates
trace (opt-3, real accelerators, the minutes-long pairing compiles),
which is where non-smoke runs happen. Smoke still hard-gates the
artifact story via zero-new-entries: a warm prewarm that RECOMPILES
instead of replaying cache entries fails regardless of wall clock.

Two more gates ride the same process:

  * tuned-not-worst — a static msm on/off A/B at --burst-lanes; the
    tuner's choice must not be slower than the WORST static config by
    more than --assert-burst-tol (measured twice before concluding).
    `--smoke` bursts at 8 lanes (a 256-lane A/B costs minutes of
    dispatch on a 1-core CPU host); accelerator runs keep the 256
    default.
  * digest invalidation — tampering the persisted profile's
    source_digest must provably re-tune (outcome "tuned", bench runs
    > 0, a "stale" profile event) instead of trusting a profile blessed
    against different kernel sources.

The bench shares the repo's persistent jit cache (jaxcache.configure),
so the first-ever run pays real XLA:CPU compiles (~6-8 min at opt-0)
and every later run replays them as cache loads — the same artifact
story the fleet rides. The tuner profile itself goes to a throwaway
temp dir so the cold path genuinely micro-benches every run.

`--smoke` (ci.sh fast tail + hostplane tier) runs tune lanes 4 /
reps 3 / burst 8 and enforces all four gates.
"""

from __future__ import annotations

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    # Canonical flag string — EXACTLY tests/conftest.py's — so the bench,
    # pytest, and the driver dryrun share persistent-cache entries for
    # the same programs.
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8"
        " --xla_backend_optimization_level=0"
    )
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path


def _wall(fn):
    t0 = time.perf_counter()
    out = fn()
    return out, time.perf_counter() - t0


def _boot(at, mode, path, args, events):
    """resolve + prewarm at the tune shape == one node boot. Returns
    (result, tune_seconds, prewarm_seconds)."""

    def obs(kind, **fields):
        if kind == "profile":
            events.append(fields["event"])

    res, t_tune = _wall(lambda: at.resolve(
        mode, path, observer=obs, lanes=args.tune_lanes, reps=args.reps,
    ))
    _, t_prewarm = _wall(
        lambda: at.aot_prewarm(res.config, lanes=(args.tune_lanes,))
    )
    return res, t_tune, t_prewarm


def _static_burst(at, msm: bool, lanes: int, reps: int) -> float:
    """Dispatch seconds for the recombine burst under a PINNED msm
    choice (the A/B the tuner's decision is judged against)."""
    import dataclasses

    dataclasses.replace(at.KernelConfig(), msm=msm).apply()
    run = at.CANDIDATES["msm"].builder(lanes)
    run()  # compile + first dispatch outside the timed region
    return min(at._timed(run) for _ in range(max(1, reps)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="CI shapes: tune lanes 4, burst 8, all gates on")
    p.add_argument("--tune-lanes", type=int, default=None,
                   help="micro-bench lane count (default: smoke 4 else 8)")
    p.add_argument("--reps", type=int, default=3,
                   help="timed reps per candidate value (min taken)")
    p.add_argument("--burst-lanes", type=int, default=None,
                   help="static A/B burst shape (default: smoke 8 else 256)")
    p.add_argument("--assert-warm-frac", type=float, default=0.10,
                   help="warm wall must be under this fraction of cold "
                        "(0 disables)")
    p.add_argument("--warm-frac-scope", choices=("tune", "boot"),
                   default=None,
                   help="what the warm fraction covers: 'tune' = "
                        "resolve only (smoke default — trace-bound CPU "
                        "geometry), 'boot' = tune + prewarm (default "
                        "otherwise — accelerator geometries where "
                        "compile dominates trace)")
    p.add_argument("--assert-burst-tol", type=float, default=0.10,
                   help="tuned choice may exceed the WORST static config "
                        "by at most this fraction (negative disables)")
    p.add_argument("--profile", default=None,
                   help="profile path (default: throwaway temp dir)")
    args = p.parse_args(argv)
    if args.tune_lanes is None:
        args.tune_lanes = 4 if args.smoke else 8
    if args.burst_lanes is None:
        args.burst_lanes = 8 if args.smoke else 256
    if args.warm_frac_scope is None:
        args.warm_frac_scope = "tune" if args.smoke else "boot"

    import jax

    from charon_tpu import jaxcache
    from charon_tpu.core import autotune as at

    jaxcache.configure(jax, cpu=jax.default_backend() == "cpu")

    tmp = None
    if args.profile:
        path = Path(args.profile)
    else:
        tmp = tempfile.TemporaryDirectory(prefix="charon-autotune-bench-")
        path = Path(tmp.name) / at.PROFILE_BASENAME

    failures: list[str] = []
    report: dict = {"smoke": args.smoke, "tune_lanes": args.tune_lanes,
                    "burst_lanes": args.burst_lanes}

    # -- COLD ----------------------------------------------------------
    cold_events: list[str] = []
    cold, t_cold_tune, t_cold_pre = _boot(at, "force", path, args,
                                          cold_events)
    t_cold = t_cold_tune + t_cold_pre
    report["cold"] = {
        "tune_seconds": round(t_cold_tune, 3),
        "prewarm_seconds": round(t_cold_pre, 3),
        "seconds": round(t_cold, 3),
        "outcome": cold.outcome,
        "bench_runs": cold.bench_runs,
        "config": cold.config.as_dict(),
        "timings": cold.timings,
    }
    print(f"# cold boot: tune {t_cold_tune:.1f} s + prewarm "
          f"{t_cold_pre:.1f} s, outcome {cold.outcome}, "
          f"{cold.bench_runs} bench runs, config {cold.config.as_dict()}")
    if cold.outcome != "tuned" or cold.bench_runs == 0:
        failures.append(
            f"cold boot did not micro-bench (outcome {cold.outcome}, "
            f"{cold.bench_runs} runs)")

    # -- tuned-not-worst burst A/B ------------------------------------
    def burst_ab():
        timings = {
            lbl: _static_burst(at, flag, args.burst_lanes, args.reps)
            for lbl, flag in (("on", True), ("off", False))
        }
        tuned_lbl = at._label(cold.config.msm)
        worst = max(timings.values())
        return timings, tuned_lbl, timings[tuned_lbl], worst

    timings, tuned_lbl, tuned_t, worst_t = burst_ab()
    tol = args.assert_burst_tol
    if tol >= 0 and tuned_t > worst_t * (1 + tol):
        print(f"# tuned choice msm={tuned_lbl} {tuned_t:.3f} s vs worst "
              f"{worst_t:.3f} s — remeasuring")
        timings, tuned_lbl, tuned_t, worst_t = burst_ab()
    report["burst"] = {
        "lanes": args.burst_lanes,
        "static_seconds": {k: round(v, 4) for k, v in timings.items()},
        "tuned_choice": tuned_lbl,
    }
    print(f"# burst A/B @ {args.burst_lanes} lanes: "
          f"{ {k: round(v, 3) for k, v in timings.items()} } — tuner "
          f"picked msm={tuned_lbl}")
    if tol >= 0 and tuned_t > worst_t * (1 + tol):
        failures.append(
            f"tuned choice msm={tuned_lbl} ({tuned_t:.3f} s) slower than "
            f"worst static ({worst_t:.3f} s) beyond {tol:.0%}")

    # -- WARM ----------------------------------------------------------
    stats0 = jaxcache.cache_stats() or {}
    scope = args.warm_frac_scope
    cold_scoped = t_cold_tune if scope == "tune" else t_cold

    def warm_once():
        ev: list[str] = []
        res, t_tune, t_pre = _boot(at, "auto", path, args, ev)
        stats = jaxcache.cache_stats() or {}
        grew = stats.get("entries", 0) - stats0.get("entries", 0)
        return res, t_tune, t_pre, ev, grew

    warm, tw_tune, tw_pre, warm_events, grew = warm_once()
    frac = args.assert_warm_frac

    def warm_scoped(t_tune, t_pre):
        return t_tune if scope == "tune" else t_tune + t_pre

    def warm_ok(res, t_tune, t_pre, g):
        if res.outcome != "hit" or res.bench_runs != 0 or g > 0:
            return False
        return not frac or warm_scoped(t_tune, t_pre) < frac * cold_scoped

    if not warm_ok(warm, tw_tune, tw_pre, grew):
        print(f"# warm boot tune {tw_tune:.3f} s + prewarm {tw_pre:.1f} s "
              f"(cold {cold_scoped:.1f} s at scope={scope}), outcome "
              f"{warm.outcome}, +{grew} cache entries — remeasuring")
        warm, tw_tune, tw_pre, warm_events, grew = warm_once()
    t_warm = warm_scoped(tw_tune, tw_pre)
    ratio = t_warm / max(cold_scoped, 1e-9)
    report["warm"] = {
        "tune_seconds": round(tw_tune, 4),
        "prewarm_seconds": round(tw_pre, 3),
        "outcome": warm.outcome,
        "bench_runs": warm.bench_runs,
        "new_cache_entries": grew,
        "frac_scope": scope,
        "frac_of_cold": round(ratio, 6),
    }
    print(f"# warm boot: tune {tw_tune:.3f} s + prewarm {tw_pre:.1f} s; "
          f"{scope} scope {t_warm:.3f} s = {ratio:.2%} of cold "
          f"{cold_scoped:.1f} s; outcome {warm.outcome}, "
          f"{warm.bench_runs} bench runs, +{grew} cache entries")
    if warm.outcome != "hit" or warm.bench_runs != 0:
        failures.append(
            f"warm boot was not a pure profile load (outcome "
            f"{warm.outcome}, {warm.bench_runs} bench runs)")
    if grew > 0:
        failures.append(
            f"warm boot wrote {grew} new compile-cache entries — prewarm "
            f"recompiled instead of replaying artifacts")
    if frac and t_warm >= frac * cold_scoped:
        failures.append(
            f"warm {scope} wall {t_warm:.3f} s is {ratio:.1%} of cold "
            f"{cold_scoped:.1f} s (gate: < {frac:.0%})")

    # -- digest invalidation ------------------------------------------
    prof = at.load_profile(path)
    prof["source_digest"] = "tampered-" + "0" * 8
    at.save_profile(prof, path)
    stale_events: list[str] = []

    def obs(kind, **fields):
        if kind == "profile":
            stale_events.append(fields["event"])

    retuned = at.resolve("auto", path, observer=obs,
                         lanes=args.tune_lanes, reps=1)
    report["digest_invalidation"] = {
        "outcome": retuned.outcome,
        "bench_runs": retuned.bench_runs,
        "events": stale_events,
    }
    print(f"# digest tamper: outcome {retuned.outcome}, "
          f"{retuned.bench_runs} bench runs, events {stale_events}")
    if (retuned.outcome != "tuned" or retuned.bench_runs == 0
            or "stale" not in stale_events):
        failures.append(
            f"source-digest tamper did not force a re-tune (outcome "
            f"{retuned.outcome}, events {stale_events})")

    # leave the process on kernel defaults, not the last trial's pins
    at.KernelConfig().apply()
    if tmp is not None:
        tmp.cleanup()

    report["cache"] = jaxcache.cache_stats() or {}
    report["failures"] = failures
    print(json.dumps(report, indent=2, sort_keys=True))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("# all autotune gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
