# Long-window axon claim probe: is the tunnel wedged or just cold?
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "axon,cpu")
t0 = time.time()
print(f"[probe] importing jax at t=0", flush=True)
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
print(f"[probe] jax imported at t={time.time()-t0:.1f}s; claiming devices...", flush=True)
d = jax.devices()
print(f"[probe] CLAIMED at t={time.time()-t0:.1f}s: {d}", flush=True)
if d[0].platform == "cpu":
    # axon plugin failed fast and jax fell back to CPU — NOT a recovered
    # tunnel; the watcher must keep waiting, not run the suite on CPU
    print("[probe] claimed platform is cpu, not the TPU: FAIL", flush=True)
    sys.exit(1)
import numpy as np
x = jax.numpy.ones((256, 256))
y = (x @ x).block_until_ready()
print(f"[probe] matmul done at t={time.time()-t0:.1f}s, sum={float(y.sum())}", flush=True)
