#!/bin/bash
# Round-4 claim-watcher: the relay PORT answering is not enough (rounds
# 3-4 saw open ports with the PJRT claim wedged), so probe the actual
# device claim in a subprocess with a generous timeout; on success run
# the full bench ladder + slot-step bench. Logs to bench_r4_auto.log.
log=/root/repo/bench_r4_auto.log
cd /root/repo
attempt=0
while true; do
  attempt=$((attempt+1))
  echo "[watch2 $(date +%H:%M:%S)] claim attempt $attempt (timeout 900s)" >> "$log"
  if timeout 900 python .claim_probe.py >> .claim_probe.log 2>&1; then
    echo "[watch2 $(date +%H:%M:%S)] CLAIM OK - launching bench ladder" >> "$log"
    BENCH_BATCHES="4096 2048 1024 512 256" python bench.py >> /root/repo/bench_r4_auto.out 2>> "$log"
    echo "[watch2 $(date +%H:%M:%S)] bench exited rc=$?" >> "$log"
    python bench_slotstep.py >> /root/repo/bench_r4_auto.out 2>> "$log"
    echo "[watch2 $(date +%H:%M:%S)] slotstep exited rc=$?" >> "$log"
    BENCH_MXU=1 python bench.py >> /root/repo/bench_r4_auto.out 2>> "$log"
    echo "[watch2 $(date +%H:%M:%S)] mxu bench exited rc=$?" >> "$log"
    python bench_dkg.py >> /root/repo/bench_r4_auto.out 2>> "$log"
    echo "[watch2 $(date +%H:%M:%S)] dkg bench exited rc=$?" >> "$log"
    echo "[watch2 $(date +%H:%M:%S)] full suite done" >> "$log"
    exit 0
  fi
  echo "[watch2 $(date +%H:%M:%S)] claim attempt $attempt failed/hung" >> "$log"
  sleep 60
done
